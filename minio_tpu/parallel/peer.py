"""Peer control-plane RPC service (cmd/peer-rest-{client,server,common}.go).

Cross-node coherence for the control plane: when one node mutates IAM or
a bucket's metadata, it fans the change notification to every peer so
their in-memory caches reload IMMEDIATELY instead of serving stale
policy until a cache happens to expire (peerRESTMethodLoadBucketMetadata
/ LoadUser / LoadPolicy, cmd/peer-rest-common.go:27-61).  The service
also exposes trace/log tails so one admin endpoint can aggregate
observability streams across the cluster (peerRESTMethodTrace :54,
peerRESTMethodLog :56).
"""

from __future__ import annotations

import threading
import time

from .rpc import RPCClient, RPCServer


def register_peer_service(rpc: RPCServer, srv) -> None:
    """Export a node's control-plane reload + observability hooks
    (peer-rest-server.go handler table).  ``srv`` is the node's
    S3Server."""

    def _evict_bucket_seen(layer, bucket: str) -> None:
        """Drop a bucket from every nested layer's existence cache so a
        peer's delete_bucket is visible here immediately rather than
        after the 3 s TTL."""
        from ..objectlayer.metacache import leaf_layers_of
        for leaf in leaf_layers_of(layer):
            getattr(leaf, "_buckets_seen", {}).pop(bucket, None)

    def reload_bucket_meta(bucket: str) -> bool:
        srv.bucket_meta.invalidate(bucket)
        _evict_bucket_seen(srv.layer, bucket)
        return True

    def reload_iam() -> bool:
        srv.iam.load()
        return True

    def trace_since(seq: int, limit: int = 500, types=None):
        """Trace-ring poll; ``types`` is the aggregator's wanted trace
        types — subsystem-span capture is only leased when a deep type
        is wanted, items are filtered server-side so http-only
        aggregation never ships deep spans over the wire.  An ABSENT
        ``types`` is a pre-deep-tracing caller (rolling upgrade): it
        gets exactly the old behavior — http records only, no deep
        lease.  The explicit sentinel ``["all"]`` streams everything."""
        from ..obs import trace as _trace
        want = set(types) if types is not None else {"http"}
        if "all" in want:
            _trace.lease_deep_ring()
            want = None
        elif want - {"http"}:
            _trace.lease_deep_ring()
        latest, items = srv.trace_hub.since(seq, limit)
        if want is not None:
            items = [i for i in items
                     if i.get("type", "http") in want]
        return {"seq": latest, "items": items}

    def log_recent(n: int = 100):
        return srv.logger.recent(n)

    def mark_change(bucket: str, object_name: str = "") -> bool:
        """A peer's write happened: mark this node's update tracker so
        cached listings for the bucket go stale immediately instead of
        after the metacache TTL (cmd/data-update-tracker.go fan-in +
        cmd/metacache-bucket.go consult).  The hot-read plane rides
        the same fan-out: an overwrite/delete on ANY node evicts this
        node's cached windows and fences its in-flight fills — a hit
        was never stale anyway (every hit revalidates against a quorum
        metadata read), the eviction frees the bytes promptly."""
        if srv.tracker is not None:
            srv.tracker.mark(bucket, object_name)
        else:
            from ..objectlayer.metacache import managers_of
            for mc in managers_of(srv.layer):
                mc.invalidate(bucket)  # no tracker: hard-drop instead
        from ..objectlayer.metacache import leaf_layers_of
        for leaf in leaf_layers_of(srv.layer):
            plane = getattr(leaf, "hotread", None)
            if plane is not None:
                if object_name:
                    plane.invalidate(bucket, object_name)
                else:
                    plane.invalidate_bucket(bucket)
        if not object_name:
            # bucket-level change (create/delete): existence cache too
            _evict_bucket_seen(srv.layer, bucket)
        return True

    # inter-node throughput probes (peerRESTMethodNetInfo role,
    # cmd/peer-rest-common.go:29-36): the caller times pushing bytes
    # up and pulling bytes back over the REAL authed RPC transport
    def netperf_upload(data: bytes = b"") -> int:
        return len(data)

    def netperf_download(n: int = 0) -> bytes:
        return b"\xa5" * min(int(n), 8 << 20)

    # -- cluster self-measurement (peerRESTMethodSpeedtest /
    # peerRESTMethodDriveSpeedtest / peerRESTMethodMetrics /
    # peerRESTMethodStartProfiling + cmd/utils.go getProfileData) -----

    def metrics_render() -> dict:
        """This node's full exposition document, server-labelled, plus
        the node name so the aggregator's health marks
        (mt_node_scrape_ok) join against the document's ``server``
        label instead of the RPC endpoint."""
        from ..admin.handlers import _render_local
        return {"node": srv.node_name,
                "doc": _render_local(srv, node=srv.node_name)}

    def profile_start(kinds: str = "cpu"):
        from ..obs import profiling
        return profiling.start(kinds)

    def profile_stop():
        """{filename: dump bytes} — the aggregator renames per node."""
        from ..obs import profiling
        return profiling.stop_dumps()

    def speedtest_object(size: int = 1 << 20, duration_s: float = 1.0,
                         concurrency: int = 0):
        from ..obs import selftest
        out = selftest.object_speedtest(srv.layer, size=size,
                                        duration_s=duration_s,
                                        concurrency=concurrency)
        out["node"] = srv.node_name
        return out

    def speedtest_drive(file_size: int = 4 << 20):
        from ..obs import selftest
        return {"node": srv.node_name,
                "drives": selftest.drive_speedtest(
                    selftest.local_drive_paths(srv.layer),
                    file_size=file_size)}

    def speedtest_tpu(size: int = 4 << 20, k: int = 4, m: int = 2,
                      block_size: int = 1 << 20):
        from ..obs import selftest
        out = selftest.tpu_codec_speedtest(size=size, k=k, m=m,
                                           block_size=block_size)
        out["node"] = srv.node_name
        return out

    def background_status():
        from ..admin.handlers import background_status as _bg
        out = _bg(srv)
        out["node"] = srv.node_name
        return out

    # telemetry-egress plane (admin `targets` / `targets/replay`
    # aggregation): this node's delivery-target state machine rows, and
    # the synchronous store replay kick (obs/egress.py)
    def target_status():
        return {"node": srv.node_name, "targets": srv.egress.status()}

    def target_replay():
        return {"node": srv.node_name,
                "replayed": srv.egress.replay_all()}

    # request X-ray + forensic planes (admin `xray` / `forensics` /
    # `healthinfo?scope=cluster` aggregation — the OBD fan-out shape,
    # cmd/healthinfo.go + peer drill-downs)
    def xray_query(api: str = "", min_duration_ms: float = 0.0,
                   errors_only: bool = False, limit: int = 100,
                   snapshot: bool = False):
        from ..admin.handlers import xray_reply
        return xray_reply(srv, api=api,
                          min_duration_ms=min_duration_ms,
                          errors_only=errors_only, limit=limit,
                          snapshot=snapshot)

    def healthinfo_collect(perf: bool = False):
        from ..admin.handlers import _drive_paths, _node_system_info
        from ..obs import healthinfo as _hi
        doc = _hi.collect(_drive_paths(srv), perf=perf)
        doc["node"] = srv.node_name
        doc["system"] = _node_system_info(srv)
        return doc

    def forensic_list():
        from ..admin.handlers import forensic_inventory
        return forensic_inventory(srv)

    def trace_tree_query(rid: str = "", api: str = "",
                         min_duration_ms: float = 0.0,
                         errors_only: bool = False, limit: int = 20,
                         rids=()):
        from ..obs import tracetree as _tt
        return _tt.tree_reply(srv, rid=rid, api=api,
                              min_duration_ms=min_duration_ms,
                              errors_only=errors_only, limit=limit,
                              rids=tuple(rids or ()))

    # SLO watchdog plane (admin `metrics-history` / `alerts`
    # aggregation): same shared builders as the local routes, so the
    # local leg and the peer leg can never drift apart in shape
    def history_query(family: str = "", window_s: float = 1800.0,
                      step_s: float = 60.0, agg: str = "last"):
        from ..admin.handlers import history_doc
        return {"node": srv.node_name,
                "doc": history_doc(srv, family=family,
                                   window_s=window_s, step_s=step_s,
                                   agg=agg, node=srv.node_name)}

    def alerts_query():
        from ..admin.handlers import alerts_reply
        return alerts_reply(srv)

    # Workload attribution plane (admin `top` v2 aggregation): the
    # same shared builder as the local route, so local and peer legs
    # can never drift apart in shape
    def metering_top():
        from ..admin.handlers import metering_top_reply
        return metering_top_reply(srv)

    rpc.register("peer", {
        "reload_bucket_meta": reload_bucket_meta,
        "reload_iam": reload_iam,
        "trace_since": trace_since,
        "log_recent": log_recent,
        "mark_change": mark_change,
        "netperf_upload": netperf_upload,
        "netperf_download": netperf_download,
        "metrics_render": metrics_render,
        "profile_start": profile_start,
        "profile_stop": profile_stop,
        "speedtest_object": speedtest_object,
        "speedtest_drive": speedtest_drive,
        "speedtest_tpu": speedtest_tpu,
        "background_status": background_status,
        "target_status": target_status,
        "target_replay": target_replay,
        "xray_query": xray_query,
        "healthinfo_collect": healthinfo_collect,
        "forensic_list": forensic_list,
        "trace_tree_query": trace_tree_query,
        "history_query": history_query,
        "alerts_query": alerts_query,
        "metering_top": metering_top,
    })


def measure_netperf(client: RPCClient,
                    probe_bytes: int = 4 << 20) -> dict:
    """Measured inter-node throughput to one peer over the real authed
    RPC transport (madmin NetPerf analog).  Returns MB/s both ways."""
    import time as _time
    blob = b"\x5a" * probe_bytes
    t0 = _time.perf_counter()
    n = client.call("peer", "netperf_upload", _idempotent=True,
                    data=blob)
    up_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    got = client.call("peer", "netperf_download", _idempotent=True,
                      n=probe_bytes)
    down_s = _time.perf_counter() - t0
    return {
        "endpoint": client.endpoint,
        "tx_MBps": round(n / up_s / 1e6, 1) if up_s > 0 else None,
        "rx_MBps": round(len(got) / down_s / 1e6, 1)
        if down_s > 0 else None,
        "probe_bytes": probe_bytes,
        "duration_ms": round((up_s + down_s) * 1e3, 2),
    }


class PeerNotifier:
    """Client side: best-effort async fan-out of control-plane change
    notifications to every other node (NotificationSys peer calls,
    cmd/notification.go)."""

    def __init__(self, clients: list[RPCClient]):
        self.clients = clients
        # one long-lived worker + bounded queue per peer: control-plane
        # churn against a dead peer must not pile up threads; dropped
        # notifications are safe (reloads are idempotent full reloads)
        self._queues: dict = {}
        self._mu = threading.Lock()

    def _queue_for(self, c: RPCClient):
        import queue as _q
        with self._mu:
            q = self._queues.get(c.endpoint)
            if q is None:
                q = _q.Queue(maxsize=64)
                self._queues[c.endpoint] = q

                def worker():
                    while True:
                        item = q.get()
                        if item is None:        # close() sentinel
                            return
                        method, kwargs = item
                        try:
                            c.call("peer", method, _idempotent=True,
                                   **kwargs)
                        except Exception:  # noqa: BLE001 — peer down:
                            pass           # it reloads fully on restart

                threading.Thread(target=worker, daemon=True,
                                 name="mt-peer-fanout").start()
            return q

    def _fanout(self, method: str, **kwargs) -> None:
        import queue as _q
        for c in self.clients:
            try:
                self._queue_for(c).put_nowait((method, kwargs))
            except _q.Full:
                pass    # backlogged peer: a later reload covers it

    def close(self) -> None:
        """Stop the notify workers (sentinel per queue)."""
        with self._mu:
            queues = list(self._queues.values())
        for q in queues:
            try:
                q.put_nowait(None)
            except Exception:  # noqa: BLE001 — full queue: worker will
                pass           # drain and exit on the next sentinel

    def bucket_meta_changed(self, bucket: str) -> None:
        self._fanout("reload_bucket_meta", bucket=bucket)

    def object_changed(self, bucket: str, object_name: str = "") -> None:
        """Async per-write fan-out feeding every peer's update tracker
        (keeps their listing caches honest without a TTL wait)."""
        self._fanout("mark_change", bucket=bucket,
                     object_name=object_name)

    def iam_changed(self) -> None:
        self._fanout("reload_iam")

    # -- observability aggregation ----------------------------------------

    def trace_tails(self, cursors: dict[str, int],
                    limit: int = 500, types=None) -> list:
        """Poll every peer's trace ring once; ``cursors`` maps endpoint →
        last-seen seq and is updated in place.  A peer first seen (or
        seen again after being unreachable at prime time) is primed at
        its CURRENT seq — a live stream never replays its history.
        ``types`` (a list of trace types, None = all) is forwarded so
        peers only capture/ship what the aggregating stream wants; the
        wire encodes "all" explicitly because an ABSENT types means a
        legacy (http-only) caller on the peer side."""
        wire_types = list(types) if types is not None else ["all"]
        merged: list = []
        for c in self.clients:
            try:
                if c.endpoint not in cursors:
                    out = c.call("peer", "trace_since", seq=0, limit=0,
                                 types=wire_types)
                    cursors[c.endpoint] = out["seq"]
                    continue
                out = c.call("peer", "trace_since",
                             seq=cursors[c.endpoint], limit=limit,
                             types=wire_types)
                if out["seq"] < cursors[c.endpoint] and not out["items"]:
                    # peer restarted: its seq space reset below our
                    # cursor — re-prime at its current head
                    cursors[c.endpoint] = out["seq"]
                    continue
                cursors[c.endpoint] = out["seq"]
                merged.extend(out["items"])
            except Exception:  # noqa: BLE001 — peer down: re-primed on
                pass           # its next successful poll
        return merged

    def log_recent_all(self, n: int = 100) -> list:
        out: list = []
        for c in self.clients:
            try:
                out.extend(c.call("peer", "log_recent", n=n))
            except Exception:  # noqa: BLE001 — downed peer: the
                pass           # aggregate serves who answered
        return out

    # -- parallel control-plane fan-out (self-measurement) -----------------

    def call_all_iter(self, method: str, timeout_s: float = 30.0,
                      idempotent: bool = True, **kwargs):
        """Call ``peer.<method>`` on every peer CONCURRENTLY, yielding
        ``(endpoint, result, error)`` as replies land (streaming
        speedtest lines).  One slow peer cannot serialize the others,
        and a peer that misses the deadline yields a ``timeout`` error
        instead of stalling the aggregate — its thread is left to die
        with the daemon flag (the RPC deadline bounds it).

        ``idempotent=False`` for one-shot methods (profile_stop: a
        replay after a half-dead keep-alive finds the session already
        stopped and would silently drop that node's dumps; peer
        speedtests: a replay re-runs the whole measured load)."""
        import queue as _q

        from ..obs import critpath as _critpath
        from ..obs import trace as _trace
        done: _q.Queue = _q.Queue()
        # propagate the causal identity into the fan-out threads so
        # every peer leg's RPC span parents under the caller's span
        # (and carry the span parent whenever the request id rides —
        # the span-discipline contract)
        rid = _trace.get_request_id()
        parent = _trace.get_span_parent()
        labels = [c.endpoint for c in self.clients]
        ends = [0] * len(self.clients)
        errs: list = [None] * len(self.clients)
        t0 = _critpath.now_ns()

        def one(i: int, c: RPCClient):
            _trace.set_request_id(rid)
            _trace.set_span_parent(parent)
            try:
                r = c.call("peer", method, _idempotent=idempotent,
                           _timeout=timeout_s, **kwargs)
                ends[i] = _critpath.now_ns()
                done.put((c.endpoint, r, ""))
            except Exception as e:  # noqa: BLE001 — peer down/slow
                errs[i] = e
                ends[i] = _critpath.now_ns()
                done.put((c.endpoint, None,
                          f"{type(e).__name__}: {e}"))

        def record_gating():
            # the aggregation gate is the LAST reply; k = n-1 makes
            # the trail histogram read "how far the slowest peer
            # trailed the rest" (an all-wait has no partial quorum)
            n = len(self.clients)
            if n > 1:
                _critpath.record("rpc", max(1, n - 1), labels,
                                 list(ends), t0, errs=errs)

        for i, c in enumerate(self.clients):
            threading.Thread(target=one, args=(i, c), daemon=True,
                             name="mt-peer-call").start()
        deadline = time.monotonic() + timeout_s
        pending = {c.endpoint for c in self.clients}
        while pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                record_gating()
                for ep in sorted(pending):
                    yield ep, None, "timeout"
                return
            try:
                ep, result, err = done.get(timeout=remaining)
            except _q.Empty:
                continue
            pending.discard(ep)
            yield ep, result, err
        record_gating()

    def call_all(self, method: str, timeout_s: float = 30.0,
                 idempotent: bool = True, **kwargs) -> list:
        """Blocking form of :meth:`call_all_iter`."""
        return list(self.call_all_iter(method, timeout_s=timeout_s,
                                       idempotent=idempotent, **kwargs))
