"""ILM lifecycle configuration + action computation.

Mirrors pkg/bucket/lifecycle/lifecycle.go (ComputeAction at
lifecycle.go:225) and rule/filter/expiration models in the same
directory.  XML wire format is the S3 LifecycleConfiguration document.
"""

from __future__ import annotations

import datetime
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from . import strip_ns

ERR_MALFORMED = "malformed lifecycle XML"


class LifecycleError(ValueError):
    pass


class Action(Enum):
    """pkg/bucket/lifecycle/lifecycle.go:37-57."""
    NONE = 0
    DELETE = 1                   # expire current version
    DELETE_VERSION = 2           # expire a noncurrent version
    TRANSITION = 3
    TRANSITION_VERSION = 4
    DELETE_MARKER_DELETE = 5     # remove an expired delete marker


def _text(el: ET.Element, tag: str) -> Optional[str]:
    child = el.find(tag)
    return child.text if child is not None else None


def _parse_days(el: ET.Element, tag: str) -> Optional[int]:
    t = _text(el, tag)
    if t is None:
        return None
    try:
        d = int(t)
    except ValueError as e:
        raise LifecycleError(f"invalid {tag}") from e
    if d <= 0:
        raise LifecycleError(f"{tag} must be positive")
    return d


def _parse_date(el: ET.Element, tag: str) -> Optional[datetime.datetime]:
    t = _text(el, tag)
    if t is None:
        return None
    try:
        dt = datetime.datetime.fromisoformat(t.replace("Z", "+00:00"))
    except ValueError as e:
        raise LifecycleError(f"invalid {tag}") from e
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt


@dataclass
class Filter:
    """Rule filter: Prefix, Tag, or And{Prefix,Tags}
    (pkg/bucket/lifecycle/filter.go)."""
    prefix: str = ""
    tags: dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_xml(cls, el: Optional[ET.Element]) -> "Filter":
        f = cls()
        if el is None:
            return f
        p = _text(el, "Prefix")
        if p is not None:
            f.prefix = p
        tag = el.find("Tag")
        if tag is not None:
            k, v = _text(tag, "Key"), _text(tag, "Value")
            if not k:
                raise LifecycleError("empty tag key in filter")
            f.tags[k] = v or ""
        and_el = el.find("And")
        if and_el is not None:
            p = _text(and_el, "Prefix")
            if p is not None:
                f.prefix = p
            for tag in and_el.findall("Tag"):
                k, v = _text(tag, "Key"), _text(tag, "Value")
                if not k:
                    raise LifecycleError("empty tag key in filter")
                f.tags[k] = v or ""
        return f

    def to_xml(self) -> ET.Element:
        el = ET.Element("Filter")
        if self.tags:
            parent = ET.SubElement(el, "And") if (
                self.prefix or len(self.tags) > 1) else el
            if self.prefix:
                ET.SubElement(parent, "Prefix").text = self.prefix
            for k, v in self.tags.items():
                t = ET.SubElement(parent, "Tag")
                ET.SubElement(t, "Key").text = k
                ET.SubElement(t, "Value").text = v
        else:
            ET.SubElement(el, "Prefix").text = self.prefix
        return el

    def matches(self, name: str, tags: dict[str, str]) -> bool:
        if not name.startswith(self.prefix):
            return False
        return all(tags.get(k) == v for k, v in self.tags.items())


@dataclass
class Rule:
    rule_id: str = ""
    status: str = "Enabled"
    filter: Filter = field(default_factory=Filter)
    # current-version expiration
    expiration_days: Optional[int] = None
    expiration_date: Optional[datetime.datetime] = None
    expired_delete_marker: bool = False
    # noncurrent versions
    noncurrent_expiration_days: Optional[int] = None
    # transitions (storage-class tiering)
    transition_days: Optional[int] = None
    transition_date: Optional[datetime.datetime] = None
    transition_storage_class: str = ""
    noncurrent_transition_days: Optional[int] = None
    noncurrent_transition_storage_class: str = ""
    abort_multipart_days: Optional[int] = None

    def validate(self) -> None:
        if self.status not in ("Enabled", "Disabled"):
            raise LifecycleError("invalid rule Status")
        if (self.expiration_days is None and self.expiration_date is None
                and not self.expired_delete_marker
                and self.noncurrent_expiration_days is None
                and self.transition_days is None
                and self.transition_date is None
                and self.noncurrent_transition_days is None
                and self.abort_multipart_days is None):
            raise LifecycleError(
                "rule has no expiration/transition/abort action")
        if self.expiration_days is not None and \
                self.expiration_date is not None:
            raise LifecycleError("Days and Date are mutually exclusive")


def _rule_from_xml(el: ET.Element) -> Rule:
    r = Rule()
    r.rule_id = _text(el, "ID") or ""
    if len(r.rule_id) > 255:
        raise LifecycleError("rule ID longer than 255")
    r.status = _text(el, "Status") or ""
    f = el.find("Filter")
    if f is None and _text(el, "Prefix") is not None:  # legacy top-level
        r.filter = Filter(prefix=_text(el, "Prefix") or "")
    else:
        r.filter = Filter.from_xml(f)
    exp = el.find("Expiration")
    if exp is not None:
        r.expiration_days = _parse_days(exp, "Days")
        r.expiration_date = _parse_date(exp, "Date")
        r.expired_delete_marker = \
            (_text(exp, "ExpiredObjectDeleteMarker") or "") == "true"
    nce = el.find("NoncurrentVersionExpiration")
    if nce is not None:
        r.noncurrent_expiration_days = _parse_days(nce, "NoncurrentDays")
    tr = el.find("Transition")
    if tr is not None:
        r.transition_days = _parse_days(tr, "Days")
        r.transition_date = _parse_date(tr, "Date")
        r.transition_storage_class = _text(tr, "StorageClass") or ""
        if not r.transition_storage_class:
            raise LifecycleError("Transition requires StorageClass")
    nct = el.find("NoncurrentVersionTransition")
    if nct is not None:
        r.noncurrent_transition_days = _parse_days(nct, "NoncurrentDays")
        r.noncurrent_transition_storage_class = \
            _text(nct, "StorageClass") or ""
    ab = el.find("AbortIncompleteMultipartUpload")
    if ab is not None:
        r.abort_multipart_days = _parse_days(ab, "DaysAfterInitiation")
    r.validate()
    return r


def _rule_to_xml(r: Rule) -> ET.Element:
    el = ET.Element("Rule")
    if r.rule_id:
        ET.SubElement(el, "ID").text = r.rule_id
    ET.SubElement(el, "Status").text = r.status
    el.append(r.filter.to_xml())
    if (r.expiration_days is not None or r.expiration_date is not None
            or r.expired_delete_marker):
        exp = ET.SubElement(el, "Expiration")
        if r.expiration_days is not None:
            ET.SubElement(exp, "Days").text = str(r.expiration_days)
        if r.expiration_date is not None:
            ET.SubElement(exp, "Date").text = \
                r.expiration_date.strftime("%Y-%m-%dT%H:%M:%SZ")
        if r.expired_delete_marker:
            ET.SubElement(exp, "ExpiredObjectDeleteMarker").text = "true"
    if r.noncurrent_expiration_days is not None:
        nce = ET.SubElement(el, "NoncurrentVersionExpiration")
        ET.SubElement(nce, "NoncurrentDays").text = \
            str(r.noncurrent_expiration_days)
    if r.transition_storage_class:
        tr = ET.SubElement(el, "Transition")
        if r.transition_days is not None:
            ET.SubElement(tr, "Days").text = str(r.transition_days)
        if r.transition_date is not None:
            ET.SubElement(tr, "Date").text = \
                r.transition_date.strftime("%Y-%m-%dT%H:%M:%SZ")
        ET.SubElement(tr, "StorageClass").text = r.transition_storage_class
    if r.noncurrent_transition_days is not None:
        nct = ET.SubElement(el, "NoncurrentVersionTransition")
        ET.SubElement(nct, "NoncurrentDays").text = \
            str(r.noncurrent_transition_days)
        ET.SubElement(nct, "StorageClass").text = \
            r.noncurrent_transition_storage_class
    if r.abort_multipart_days is not None:
        ab = ET.SubElement(el, "AbortIncompleteMultipartUpload")
        ET.SubElement(ab, "DaysAfterInitiation").text = \
            str(r.abort_multipart_days)
    return el


@dataclass
class ObjectOpts:
    """Inputs to ComputeAction (pkg/bucket/lifecycle/lifecycle.go:198)."""
    name: str
    mod_time_ns: int = 0
    user_tags: dict[str, str] = field(default_factory=dict)
    is_latest: bool = True
    delete_marker: bool = False
    num_versions: int = 1
    # for noncurrent versions: when the *successor* was written, i.e. the
    # moment this version became noncurrent
    successor_mod_time_ns: int = 0


@dataclass
class Lifecycle:
    rules: list[Rule] = field(default_factory=list)

    @classmethod
    def parse(cls, data: bytes) -> "Lifecycle":
        try:
            root = ET.fromstring(data)
        except ET.ParseError as e:
            raise LifecycleError(ERR_MALFORMED) from e
        strip_ns(root)
        if root.tag != "LifecycleConfiguration":
            raise LifecycleError(ERR_MALFORMED)
        rules = [_rule_from_xml(r) for r in root.findall("Rule")]
        if not rules:
            raise LifecycleError("at least one Rule required")
        if len(rules) > 1000:
            raise LifecycleError("more than 1000 rules")
        ids = [r.rule_id for r in rules if r.rule_id]
        if len(ids) != len(set(ids)):
            raise LifecycleError("duplicate rule ID")
        return cls(rules=rules)

    def to_xml(self) -> bytes:
        root = ET.Element(
            "LifecycleConfiguration",
            xmlns="http://s3.amazonaws.com/doc/2006-03-01/")
        for r in self.rules:
            root.append(_rule_to_xml(r))
        return (b'<?xml version="1.0" encoding="UTF-8"?>' +
                ET.tostring(root))

    # -- evaluation --------------------------------------------------------

    def _filtered(self, obj: ObjectOpts):
        for r in self.rules:
            if r.status != "Enabled":
                continue
            if r.filter.matches(obj.name, obj.user_tags):
                yield r

    def compute_action(self, obj: ObjectOpts,
                       now_ns: Optional[int] = None) -> Action:
        """pkg/bucket/lifecycle/lifecycle.go:225 ComputeAction."""
        if now_ns is None:
            now_ns = int(datetime.datetime.now(
                datetime.timezone.utc).timestamp() * 1e9)
        day_ns = 24 * 3600 * 1e9
        for r in self._filtered(obj):
            if not obj.is_latest:
                if r.noncurrent_expiration_days is not None and \
                        obj.successor_mod_time_ns:
                    if now_ns >= obj.successor_mod_time_ns + \
                            r.noncurrent_expiration_days * day_ns:
                        return Action.DELETE_VERSION
                if r.noncurrent_transition_days is not None and \
                        obj.successor_mod_time_ns:
                    if now_ns >= obj.successor_mod_time_ns + \
                            r.noncurrent_transition_days * day_ns:
                        return Action.TRANSITION_VERSION
                continue
            if obj.delete_marker:
                # a delete marker with no other versions "expires" when the
                # rule asks for ExpiredObjectDeleteMarker, or when plain
                # Days elapse (cmd/data-crawler.go lifecycle path)
                if obj.num_versions == 1 and (
                        r.expired_delete_marker or
                        (r.expiration_days is not None and
                         now_ns >= obj.mod_time_ns +
                         r.expiration_days * day_ns)):
                    return Action.DELETE_MARKER_DELETE
                continue
            if r.expiration_date is not None and \
                    now_ns >= r.expiration_date.timestamp() * 1e9:
                return Action.DELETE
            if r.expiration_days is not None and \
                    now_ns >= obj.mod_time_ns + r.expiration_days * day_ns:
                return Action.DELETE
            if r.transition_date is not None and \
                    now_ns >= r.transition_date.timestamp() * 1e9:
                return Action.TRANSITION
            if r.transition_days is not None and \
                    now_ns >= obj.mod_time_ns + r.transition_days * day_ns:
                return Action.TRANSITION
        return Action.NONE

    def transition_storage_class(self, obj: ObjectOpts,
                                 now_ns: Optional[int] = None) -> str:
        """Destination storage class of the transition rule that is
        actually DUE — the same rule compute_action returns TRANSITION
        for, not merely the first matching rule."""
        if now_ns is None:
            now_ns = int(datetime.datetime.now(
                datetime.timezone.utc).timestamp() * 1e9)
        day_ns = 24 * 3600 * 1e9
        for r in self._filtered(obj):
            if not obj.is_latest:
                if r.noncurrent_transition_days is not None and \
                        obj.successor_mod_time_ns and \
                        now_ns >= obj.successor_mod_time_ns + \
                        r.noncurrent_transition_days * day_ns:
                    return r.noncurrent_transition_storage_class
                continue
            if r.transition_date is not None and \
                    now_ns >= r.transition_date.timestamp() * 1e9 and \
                    r.transition_storage_class:
                return r.transition_storage_class
            if r.transition_days is not None and \
                    now_ns >= obj.mod_time_ns + \
                    r.transition_days * day_ns and \
                    r.transition_storage_class:
                return r.transition_storage_class
        return ""

    def has_active_rules(self, prefix: str = "") -> bool:
        return any(
            r.status == "Enabled" and (
                r.filter.prefix.startswith(prefix) or
                prefix.startswith(r.filter.prefix))
            for r in self.rules)
