"""Bucket default-encryption configuration — pkg/bucket/encryption/
bucket-sse-config.go.

ServerSideEncryptionConfiguration XML selecting SSE-S3 (AES256) or
SSE-KMS (aws:kms + optional key id) to auto-apply on PUTs without
explicit SSE headers.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from dataclasses import dataclass

from . import strip_ns


class BucketSSEError(ValueError):
    pass


@dataclass
class SSEConfig:
    algorithm: str = ""          # "AES256" | "aws:kms"
    kms_key_id: str = ""

    @classmethod
    def parse(cls, data: bytes) -> "SSEConfig":
        try:
            root = ET.fromstring(data)
        except ET.ParseError as e:
            raise BucketSSEError("malformed encryption XML") from e
        strip_ns(root)
        if root.tag != "ServerSideEncryptionConfiguration":
            raise BucketSSEError("malformed encryption XML")
        rules = root.findall("Rule")
        if len(rules) != 1:
            raise BucketSSEError("exactly one Rule required")
        by_default = rules[0].find("ApplyServerSideEncryptionByDefault")
        if by_default is None:
            raise BucketSSEError(
                "ApplyServerSideEncryptionByDefault required")
        algo = by_default.findtext("SSEAlgorithm") or ""
        if algo not in ("AES256", "aws:kms"):
            raise BucketSSEError(f"unsupported SSEAlgorithm {algo!r}")
        return cls(algorithm=algo,
                   kms_key_id=by_default.findtext("KMSMasterKeyID") or "")

    def to_xml(self) -> bytes:
        root = ET.Element(
            "ServerSideEncryptionConfiguration",
            xmlns="http://s3.amazonaws.com/doc/2006-03-01/")
        rule = ET.SubElement(root, "Rule")
        by_default = ET.SubElement(rule,
                                   "ApplyServerSideEncryptionByDefault")
        ET.SubElement(by_default, "SSEAlgorithm").text = self.algorithm
        if self.kms_key_id:
            ET.SubElement(by_default, "KMSMasterKeyID").text = \
                self.kms_key_id
        return (b'<?xml version="1.0" encoding="UTF-8"?>' +
                ET.tostring(root))
