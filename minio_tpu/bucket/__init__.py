"""Bucket feature configuration models (pkg/bucket/* in the reference).

Each module parses/validates/serializes one S3 bucket-level configuration
document (XML unless noted) and exposes the evaluation logic the data path
needs (lifecycle ComputeAction, replication decisions, notification rule
matching, object-lock retention checks).
"""

import xml.etree.ElementTree as ET


def strip_ns(root: ET.Element) -> None:
    """Drop XML namespaces in-place so configs parse uniformly whether or
    not the client set xmlns (S3 accepts both)."""
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
