"""S3 tagging (bucket + object) — pkg/tags/tags.go.

Validation limits per the reference: object ≤ 10 tags, bucket ≤ 50,
key ≤ 128 chars, value ≤ 256 chars, unique keys.  Supports both the XML
Tagging document and the `x-amz-tagging` URL-encoded header form.
"""

from __future__ import annotations

import urllib.parse
import xml.etree.ElementTree as ET

from . import strip_ns


class TagError(ValueError):
    def __init__(self, code: str, msg: str):
        super().__init__(msg)
        self.code = code


def _validate(tags: dict[str, str], is_object: bool) -> None:
    limit = 10 if is_object else 50
    if len(tags) > limit:
        raise TagError("BadRequest" if not is_object else "InvalidTag",
                       f"more than {limit} tags")
    for k, v in tags.items():
        if not k or len(k) > 128:
            raise TagError("InvalidTag", "tag key empty or too long")
        if len(v) > 256:
            raise TagError("InvalidTag", "tag value too long")


def parse_xml(data: bytes, is_object: bool = True) -> dict[str, str]:
    try:
        root = ET.fromstring(data)
    except ET.ParseError as e:
        raise TagError("MalformedXML", "bad tagging XML") from e
    strip_ns(root)
    if root.tag != "Tagging":
        raise TagError("MalformedXML", "bad tagging XML")
    tagset = root.find("TagSet")
    if tagset is None:
        raise TagError("MalformedXML", "missing TagSet")
    tags: dict[str, str] = {}
    for t in tagset.findall("Tag"):
        k = t.findtext("Key") or ""
        v = t.findtext("Value") or ""
        if k in tags:
            raise TagError("InvalidTag", "duplicate tag key")
        tags[k] = v
    _validate(tags, is_object)
    return tags


def parse_header(value: str, is_object: bool = True) -> dict[str, str]:
    """`x-amz-tagging: k1=v1&k2=v2` (PutObject tagging header)."""
    tags: dict[str, str] = {}
    for k, v in urllib.parse.parse_qsl(value, keep_blank_values=True):
        if k in tags:
            raise TagError("InvalidTag", "duplicate tag key")
        tags[k] = v
    _validate(tags, is_object)
    return tags


def to_header(tags: dict[str, str]) -> str:
    return urllib.parse.urlencode(tags)


def to_xml(tags: dict[str, str]) -> bytes:
    root = ET.Element(
        "Tagging", xmlns="http://s3.amazonaws.com/doc/2006-03-01/")
    tagset = ET.SubElement(root, "TagSet")
    for k, v in tags.items():
        t = ET.SubElement(tagset, "Tag")
        ET.SubElement(t, "Key").text = k
        ET.SubElement(t, "Value").text = v
    return b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)
