"""Object lock (WORM) configuration, retention and legal hold.

Mirrors pkg/bucket/object/lock/lock.go: bucket ObjectLockConfiguration
XML, per-object retention (GOVERNANCE/COMPLIANCE + retain-until-date) and
legal hold, plus the enforcement predicate used on deletes
(cmd/bucket-object-lock.go enforceRetentionForDeletion).
"""

from __future__ import annotations

import datetime
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Optional

GOVERNANCE = "GOVERNANCE"
COMPLIANCE = "COMPLIANCE"

# metadata keys on the object (x-amz-* headers are persisted verbatim)
AMZ_OBJECT_LOCK_MODE = "x-amz-object-lock-mode"
AMZ_OBJECT_LOCK_RETAIN_UNTIL = "x-amz-object-lock-retain-until-date"
AMZ_OBJECT_LOCK_LEGAL_HOLD = "x-amz-object-lock-legal-hold"


class ObjectLockError(ValueError):
    pass


from . import strip_ns as _strip_ns  # noqa: E402 — shared XML helper




@dataclass
class LockConfig:
    """Bucket-level default retention (ObjectLockConfiguration)."""
    enabled: bool = False
    mode: str = ""               # "" | GOVERNANCE | COMPLIANCE
    days: Optional[int] = None
    years: Optional[int] = None

    @classmethod
    def parse(cls, data: bytes) -> "LockConfig":
        try:
            root = ET.fromstring(data)
        except ET.ParseError as e:
            raise ObjectLockError("malformed object-lock XML") from e
        _strip_ns(root)
        if root.tag != "ObjectLockConfiguration":
            raise ObjectLockError("malformed object-lock XML")
        cfg = cls()
        cfg.enabled = (root.findtext("ObjectLockEnabled") or "") == "Enabled"
        rule = root.find("Rule")
        if rule is not None:
            ret = rule.find("DefaultRetention")
            if ret is None:
                raise ObjectLockError("Rule requires DefaultRetention")
            cfg.mode = ret.findtext("Mode") or ""
            if cfg.mode not in (GOVERNANCE, COMPLIANCE):
                raise ObjectLockError("invalid retention Mode")
            days, years = ret.findtext("Days"), ret.findtext("Years")
            if (days is None) == (years is None):
                raise ObjectLockError(
                    "exactly one of Days or Years required")
            if days is not None:
                cfg.days = int(days)
                if cfg.days <= 0:
                    raise ObjectLockError("Days must be positive")
            if years is not None:
                cfg.years = int(years)
                if cfg.years <= 0:
                    raise ObjectLockError("Years must be positive")
        return cfg

    def to_xml(self) -> bytes:
        root = ET.Element(
            "ObjectLockConfiguration",
            xmlns="http://s3.amazonaws.com/doc/2006-03-01/")
        ET.SubElement(root, "ObjectLockEnabled").text = "Enabled"
        if self.mode:
            rule = ET.SubElement(root, "Rule")
            ret = ET.SubElement(rule, "DefaultRetention")
            ET.SubElement(ret, "Mode").text = self.mode
            if self.days is not None:
                ET.SubElement(ret, "Days").text = str(self.days)
            if self.years is not None:
                ET.SubElement(ret, "Years").text = str(self.years)
        return (b'<?xml version="1.0" encoding="UTF-8"?>' +
                ET.tostring(root))

    def default_retention_headers(self, now: Optional[datetime.datetime]
                                  = None) -> dict[str, str]:
        """Metadata to stamp on new objects when a default rule exists."""
        if not self.mode:
            return {}
        now = now or datetime.datetime.now(datetime.timezone.utc)
        days = (self.days or 0) + 365 * (self.years or 0)
        until = now + datetime.timedelta(days=days)
        return {
            AMZ_OBJECT_LOCK_MODE: self.mode,
            AMZ_OBJECT_LOCK_RETAIN_UNTIL:
                until.strftime("%Y-%m-%dT%H:%M:%SZ"),
        }


@dataclass
class Retention:
    mode: str = ""
    retain_until: Optional[datetime.datetime] = None

    @classmethod
    def parse(cls, data: bytes) -> "Retention":
        try:
            root = ET.fromstring(data)
        except ET.ParseError as e:
            raise ObjectLockError("malformed retention XML") from e
        _strip_ns(root)
        if root.tag != "Retention":
            raise ObjectLockError("malformed retention XML")
        mode = root.findtext("Mode") or ""
        if mode not in (GOVERNANCE, COMPLIANCE):
            raise ObjectLockError("invalid retention Mode")
        until = root.findtext("RetainUntilDate") or ""
        try:
            dt = datetime.datetime.fromisoformat(
                until.replace("Z", "+00:00"))
        except ValueError as e:
            raise ObjectLockError("invalid RetainUntilDate") from e
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=datetime.timezone.utc)
        if dt <= datetime.datetime.now(datetime.timezone.utc):
            raise ObjectLockError("RetainUntilDate must be in the future")
        return cls(mode=mode, retain_until=dt)

    @classmethod
    def from_metadata(cls, meta: dict[str, str]) -> "Retention":
        mode = meta.get(AMZ_OBJECT_LOCK_MODE, "")
        until_s = meta.get(AMZ_OBJECT_LOCK_RETAIN_UNTIL, "")
        until = None
        if until_s:
            try:
                until = datetime.datetime.fromisoformat(
                    until_s.replace("Z", "+00:00"))
                if until.tzinfo is None:
                    until = until.replace(tzinfo=datetime.timezone.utc)
            except ValueError:
                until = None
        return cls(mode=mode, retain_until=until)

    def to_xml(self) -> bytes:
        root = ET.Element(
            "Retention", xmlns="http://s3.amazonaws.com/doc/2006-03-01/")
        ET.SubElement(root, "Mode").text = self.mode
        if self.retain_until:
            ET.SubElement(root, "RetainUntilDate").text = \
                self.retain_until.astimezone(
                    datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
        return (b'<?xml version="1.0" encoding="UTF-8"?>' +
                ET.tostring(root))

    def active(self, now: Optional[datetime.datetime] = None) -> bool:
        if not self.mode or self.retain_until is None:
            return False
        now = now or datetime.datetime.now(datetime.timezone.utc)
        return now < self.retain_until


def legal_hold_from_xml(data: bytes) -> str:
    try:
        root = ET.fromstring(data)
    except ET.ParseError as e:
        raise ObjectLockError("malformed legal hold XML") from e
    _strip_ns(root)
    status = root.findtext("Status") or ""
    if status not in ("ON", "OFF"):
        raise ObjectLockError("invalid legal hold Status")
    return status


def legal_hold_to_xml(status: str) -> bytes:
    root = ET.Element(
        "LegalHold", xmlns="http://s3.amazonaws.com/doc/2006-03-01/")
    ET.SubElement(root, "Status").text = status or "OFF"
    return b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)


def check_delete_allowed(meta: dict[str, str],
                         governance_bypass: bool = False,
                         now: Optional[datetime.datetime] = None) -> bool:
    """enforceRetentionForDeletion (cmd/bucket-object-lock.go): True iff
    deleting this exact version is permitted."""
    if meta.get(AMZ_OBJECT_LOCK_LEGAL_HOLD, "").upper() == "ON":
        return False
    ret = Retention.from_metadata(meta)
    if not ret.active(now):
        return True
    if ret.mode == COMPLIANCE:
        return False
    return governance_bypass
