"""Bucket policy — pkg/bucket/policy (policy.go, statement.go).

Bucket policies are AWS JSON policy documents *with Principals*; unlike
IAM user policies they grant anonymous or cross-user access scoped to a
single bucket.  Evaluation reuses the IAM engine's statement matching,
adding a principal check (`"*"` or specific access keys).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..iam import policy as iampol


class BucketPolicyError(ValueError):
    pass


@dataclass
class BPStatement(iampol.Statement):
    principals: list[str] = field(default_factory=list)

    def matches_principal(self, who: str) -> bool:
        # who="" means anonymous; "*" matches everyone including anonymous
        return any(p == "*" or p == who for p in self.principals)

    @classmethod
    def from_dict(cls, d: dict) -> "BPStatement":
        base = iampol.Statement.from_dict(d)
        # conditions must be {op: {key: value}} with operators the engine
        # evaluates — reject anything else at PUT time so a Deny can never
        # be silently skipped at request time (fail-closed by construction)
        if not isinstance(base.conditions, dict) or any(
                not isinstance(kv, dict) for kv in base.conditions.values()):
            raise BucketPolicyError("invalid Condition block")
        supported = {"StringEquals", "StringNotEquals", "StringLike"}
        unknown = set(base.conditions) - supported
        if unknown:
            raise BucketPolicyError(
                f"unsupported condition operator(s): {sorted(unknown)}")
        pr = d.get("Principal", {})
        if pr == "*":
            principals = ["*"]
        elif isinstance(pr, dict):
            aws = pr.get("AWS", [])
            principals = aws if isinstance(aws, list) else [aws]
        else:
            raise BucketPolicyError("invalid Principal")
        if not principals:
            raise BucketPolicyError("Principal required in bucket policy")
        return cls(effect=base.effect, actions=base.actions,
                   resources=base.resources, conditions=base.conditions,
                   principals=principals)

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["Principal"] = {"AWS": self.principals}
        return d


@dataclass
class BucketPolicy:
    version: str = "2012-10-17"
    statements: list[BPStatement] = field(default_factory=list)

    @classmethod
    def parse(cls, data: bytes, bucket: str = "") -> "BucketPolicy":
        try:
            d = json.loads(data)
        except json.JSONDecodeError as e:
            raise BucketPolicyError("malformed policy JSON") from e
        sts = d.get("Statement", [])
        if isinstance(sts, dict):
            sts = [sts]
        if not sts:
            raise BucketPolicyError("Statement required")
        pol = cls(version=d.get("Version", "2012-10-17"),
                  statements=[BPStatement.from_dict(x) for x in sts])
        if bucket:
            for st in pol.statements:
                for res in st.resources:
                    plain = res.removeprefix("arn:aws:s3:::")
                    if not (plain == bucket or
                            plain.startswith(bucket + "/") or
                            iampol._match(plain.split("/", 1)[0], bucket)):
                        raise BucketPolicyError(
                            f"resource {res} outside bucket {bucket}")
        return pol

    def to_json(self) -> bytes:
        return json.dumps({
            "Version": self.version,
            "Statement": [s.to_dict() for s in self.statements]}).encode()

    def is_allowed(self, who: str, action: str, resource: str = "",
                   context: dict | None = None) -> bool | None:
        """Three-valued: True=allow, False=explicit deny, None=no opinion
        (lets IAM user policy decide) — mirrors how cmd/auth-handler.go
        combines bucket policy with IAM."""
        context = context or {}
        verdict: bool | None = None
        for st in self.statements:
            if not (st.matches_principal(who)
                    and st.matches_action(action)
                    and st.matches_resource(resource)
                    and st.matches_conditions(context)):
                continue
            if st.effect == "Deny":
                return False
            verdict = True
        return verdict
