"""Bucket replication configuration — pkg/bucket/replication/*.go.

ReplicationConfiguration XML with prioritized rules, each carrying a
Destination ARN, optional filter, and DeleteMarkerReplication /
DeleteReplication toggles.  `replicate()` is the decision predicate the
data path calls (cmd/bucket-replication.go:100 mustReplicate).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Optional

from . import strip_ns
from .lifecycle import Filter  # same Prefix/Tag/And shape


class ReplicationError(ValueError):
    pass


@dataclass
class Rule:
    rule_id: str = ""
    status: str = "Enabled"
    priority: int = 0
    filter: Filter = field(default_factory=Filter)
    destination_arn: str = ""    # arn:minio:replication:<region>:<id>:<bucket>
    storage_class: str = ""
    delete_marker_replication: bool = False
    delete_replication: bool = False


@dataclass
class Config:
    role: str = ""
    rules: list[Rule] = field(default_factory=list)

    @classmethod
    def parse(cls, data: bytes) -> "Config":
        try:
            root = ET.fromstring(data)
        except ET.ParseError as e:
            raise ReplicationError("malformed replication XML") from e
        strip_ns(root)
        if root.tag != "ReplicationConfiguration":
            raise ReplicationError("malformed replication XML")
        cfg = cls(role=root.findtext("Role") or "")
        for rel in root.findall("Rule"):
            r = Rule()
            r.rule_id = rel.findtext("ID") or ""
            r.status = rel.findtext("Status") or ""
            if r.status not in ("Enabled", "Disabled"):
                raise ReplicationError("invalid rule Status")
            r.priority = int(rel.findtext("Priority") or 0)
            r.filter = Filter.from_xml(rel.find("Filter"))
            dest = rel.find("Destination")
            if dest is None or not (dest.findtext("Bucket") or ""):
                raise ReplicationError("rule requires Destination/Bucket")
            r.destination_arn = dest.findtext("Bucket") or ""
            r.storage_class = dest.findtext("StorageClass") or ""
            dmr = rel.find("DeleteMarkerReplication")
            if dmr is not None:
                r.delete_marker_replication = \
                    (dmr.findtext("Status") or "") == "Enabled"
            dr = rel.find("DeleteReplication")
            if dr is not None:
                r.delete_replication = \
                    (dr.findtext("Status") or "") == "Enabled"
            cfg.rules.append(r)
        if not cfg.rules:
            raise ReplicationError("at least one Rule required")
        ids = [r.rule_id for r in cfg.rules if r.rule_id]
        if len(ids) != len(set(ids)):
            raise ReplicationError("duplicate rule ID")
        prios = [r.priority for r in cfg.rules]
        if len(prios) != len(set(prios)):
            raise ReplicationError("duplicate rule Priority")
        return cfg

    def to_xml(self) -> bytes:
        root = ET.Element(
            "ReplicationConfiguration",
            xmlns="http://s3.amazonaws.com/doc/2006-03-01/")
        if self.role:
            ET.SubElement(root, "Role").text = self.role
        for r in sorted(self.rules, key=lambda x: -x.priority):
            rel = ET.SubElement(root, "Rule")
            if r.rule_id:
                ET.SubElement(rel, "ID").text = r.rule_id
            ET.SubElement(rel, "Status").text = r.status
            ET.SubElement(rel, "Priority").text = str(r.priority)
            rel.append(r.filter.to_xml())
            dest = ET.SubElement(rel, "Destination")
            ET.SubElement(dest, "Bucket").text = r.destination_arn
            if r.storage_class:
                ET.SubElement(dest, "StorageClass").text = r.storage_class
            dmr = ET.SubElement(rel, "DeleteMarkerReplication")
            ET.SubElement(dmr, "Status").text = \
                "Enabled" if r.delete_marker_replication else "Disabled"
            dr = ET.SubElement(rel, "DeleteReplication")
            ET.SubElement(dr, "Status").text = \
                "Enabled" if r.delete_replication else "Disabled"
        return (b'<?xml version="1.0" encoding="UTF-8"?>' +
                ET.tostring(root))

    # -- decision ---------------------------------------------------------

    def match_rule(self, name: str, tags: dict[str, str]) -> Optional[Rule]:
        """Highest-priority enabled rule matching the object."""
        best: Optional[Rule] = None
        for r in self.rules:
            if r.status != "Enabled":
                continue
            if not r.filter.matches(name, tags):
                continue
            if best is None or r.priority > best.priority:
                best = r
        return best

    def replicate(self, name: str, tags: dict[str, str],
                  delete_marker: bool = False,
                  versioned_delete: bool = False) -> Optional[Rule]:
        """mustReplicate: returns the rule to apply, or None."""
        r = self.match_rule(name, tags)
        if r is None:
            return None
        if delete_marker and not r.delete_marker_replication:
            return None
        if versioned_delete and not r.delete_replication:
            return None
        return r
