"""Bucket notification configuration — pkg/event/config.go + rules.go.

NotificationConfiguration XML holding Queue/Topic/CloudFunction
configurations; each maps a set of event names + prefix/suffix filter
rules to a target ARN.  `match()` implements the rules-map lookup the
event system uses to route an event to targets
(pkg/event/rulesmap.go, pkg/event/targetidset.go).
"""

from __future__ import annotations

import fnmatch
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field

from . import strip_ns


class NotificationError(ValueError):
    pass


# pkg/event/name.go — supported event names (wildcard forms expand)
EVENT_NAMES = {
    "s3:ObjectCreated:*", "s3:ObjectCreated:Put", "s3:ObjectCreated:Post",
    "s3:ObjectCreated:Copy", "s3:ObjectCreated:CompleteMultipartUpload",
    "s3:ObjectCreated:PutRetention", "s3:ObjectCreated:PutLegalHold",
    "s3:ObjectCreated:PutTagging", "s3:ObjectCreated:DeleteTagging",
    "s3:ObjectRemoved:*", "s3:ObjectRemoved:Delete",
    "s3:ObjectRemoved:DeleteMarkerCreated",
    "s3:ObjectAccessed:*", "s3:ObjectAccessed:Get",
    "s3:ObjectAccessed:Head",
    "s3:Replication:*", "s3:Replication:OperationFailedReplication",
    "s3:Replication:OperationCompletedReplication",
    "s3:ObjectRestore:Post", "s3:ObjectRestore:Completed",
}


def _expand(name: str) -> set[str]:
    if name.endswith(":*"):
        prefix = name[:-1]
        return {n for n in EVENT_NAMES
                if n.startswith(prefix) and not n.endswith("*")}
    return {name}


@dataclass
class TargetConfig:
    arn: str = ""
    events: set[str] = field(default_factory=set)  # expanded names
    prefix: str = ""
    suffix: str = ""

    def matches(self, event_name: str, key: str) -> bool:
        if event_name not in self.events:
            return False
        if self.prefix and not key.startswith(self.prefix):
            return False
        if self.suffix and not key.endswith(self.suffix):
            return False
        return True


@dataclass
class Config:
    targets: list[TargetConfig] = field(default_factory=list)

    @classmethod
    def parse(cls, data: bytes,
              valid_arns: set[str] | None = None) -> "Config":
        try:
            root = ET.fromstring(data)
        except ET.ParseError as e:
            raise NotificationError("malformed notification XML") from e
        strip_ns(root)
        if root.tag != "NotificationConfiguration":
            raise NotificationError("malformed notification XML")
        cfg = cls()
        for kind, arn_tag in (("QueueConfiguration", "Queue"),
                              ("TopicConfiguration", "Topic"),
                              ("CloudFunctionConfiguration",
                               "CloudFunction")):
            for qel in root.findall(kind):
                t = TargetConfig(arn=qel.findtext(arn_tag) or "")
                if not t.arn:
                    raise NotificationError(f"missing {arn_tag} ARN")
                if valid_arns is not None and t.arn not in valid_arns:
                    raise NotificationError(f"unknown ARN {t.arn}")
                for ev in qel.findall("Event"):
                    name = ev.text or ""
                    if name not in EVENT_NAMES:
                        raise NotificationError(f"unknown event {name}")
                    t.events |= _expand(name)
                if not t.events:
                    raise NotificationError("no events configured")
                filt = qel.find("Filter")
                if filt is not None:
                    key = filt.find("S3Key")
                    for rule in (key.findall("FilterRule")
                                 if key is not None else []):
                        n = (rule.findtext("Name") or "").lower()
                        v = rule.findtext("Value") or ""
                        if n == "prefix":
                            t.prefix = v
                        elif n == "suffix":
                            t.suffix = v
                        else:
                            raise NotificationError(
                                f"bad filter rule name {n}")
                cfg.targets.append(t)
        return cfg

    def to_xml(self) -> bytes:
        root = ET.Element(
            "NotificationConfiguration",
            xmlns="http://s3.amazonaws.com/doc/2006-03-01/")
        for t in self.targets:
            qel = ET.SubElement(root, "QueueConfiguration")
            ET.SubElement(qel, "Queue").text = t.arn
            for name in sorted(t.events):
                ET.SubElement(qel, "Event").text = name
            if t.prefix or t.suffix:
                filt = ET.SubElement(qel, "Filter")
                key = ET.SubElement(filt, "S3Key")
                if t.prefix:
                    r = ET.SubElement(key, "FilterRule")
                    ET.SubElement(r, "Name").text = "prefix"
                    ET.SubElement(r, "Value").text = t.prefix
                if t.suffix:
                    r = ET.SubElement(key, "FilterRule")
                    ET.SubElement(r, "Name").text = "suffix"
                    ET.SubElement(r, "Value").text = t.suffix
        return (b'<?xml version="1.0" encoding="UTF-8"?>' +
                ET.tostring(root))

    def match(self, event_name: str, key: str) -> set[str]:
        """ARNs to deliver this event to."""
        return {t.arn for t in self.targets if t.matches(event_name, key)}


def match_pattern(pattern: str, value: str) -> bool:
    """Event-pattern glob used by ListenNotification prefixes."""
    return fnmatch.fnmatchcase(value, pattern) if pattern else True
