"""Bucket quota — cmd/bucket-quota.go + pkg/madmin BucketQuota.

JSON document {"quota": bytes, "quotatype": "hard"|"fifo"}; the hard
quota rejects PUTs that would exceed the limit (enforced against the
crawler's usage accounting).
"""

from __future__ import annotations

import json
from dataclasses import dataclass


class QuotaError(ValueError):
    pass


HARD = "hard"
FIFO = "fifo"


@dataclass
class Quota:
    quota: int = 0
    quota_type: str = HARD

    @classmethod
    def parse(cls, data: bytes) -> "Quota":
        try:
            doc = json.loads(data)
        except json.JSONDecodeError as e:
            raise QuotaError("malformed quota JSON") from e
        q = int(doc.get("quota", 0))
        qt = doc.get("quotatype", HARD)
        if qt not in (HARD, FIFO):
            raise QuotaError(f"invalid quotatype {qt!r}")
        if q < 0:
            raise QuotaError("quota must be non-negative")
        return cls(quota=q, quota_type=qt)

    def to_json(self) -> bytes:
        return json.dumps(
            {"quota": self.quota, "quotatype": self.quota_type}).encode()

    def allows(self, current_usage: int, incoming: int) -> bool:
        """Hard-quota admission check (cmd/bucket-quota.go
        enforceBucketQuota)."""
        if self.quota <= 0 or self.quota_type != HARD:
            return True
        return current_usage + incoming <= self.quota
