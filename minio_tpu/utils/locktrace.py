"""Runtime lock-order / deadlock detector — the dynamic half of the
concurrency analysis plane (docs/static-analysis.md).

A GIL'd runtime has no -race flag, but the failure class the Go race
detector guards the reference against still exists here one level up:
*lock-order inversion*.  Two threads taking the same pair of locks in
opposite order deadlock exactly once a year, in production, under
load.  This module makes that ordering observable and assertable:

* :func:`mtlock` / :func:`mtrlock` are drop-in factories the data
  plane uses instead of ``threading.Lock()`` / ``RLock()``.  When
  tracing is OFF (the default) they return the plain primitive — zero
  wrapper, zero overhead on the hot path.  When tracing is ON
  (``MT_LOCK_TRACE=1`` in the environment, or :func:`enable` before
  the locks are constructed) they return a :class:`TracedLock`.

* every traced acquisition records, per thread, the stack of locks
  currently held; holding ``a`` while acquiring ``b`` adds the edge
  ``a -> b`` to a process-global *lock-order graph* keyed by lock
  NAME (instances aggregate — ``storage.writer-queue`` is one node no
  matter how many drives own one).  Same-name nesting (two drives'
  queues, dsync's per-resource locks) is recorded separately as a
  ``self_nest`` count, not an edge: instance-level ordering is the
  caller's contract and a name-level self-edge would report every
  such pattern as a false cycle.

* :func:`cycles` runs SCC detection over the graph — any strongly
  connected component larger than one lock is a potential AB/BA
  deadlock, reported with the witness edges and the first acquisition
  site of each direction.  :func:`assert_acyclic` raises with that
  report; the tier-1 soak smoke and the chaos drills call it after
  driving real traffic through a fault timeline.

* *long holds under contention*: a lock held longer than
  ``long_hold_s`` (default 0.5s, env ``MT_LOCK_TRACE_LONG_HOLD_S``)
  while at least one other thread was blocked waiting on it is
  recorded — the slow-under-lock class the static ``lock-discipline``
  rule hunts lexically, caught dynamically when it hides behind a
  call boundary.

Scrape families (admin/metrics.py, idle contract: tracing off or an
empty graph emits nothing): ``mt_lock_order_edges_total``,
``mt_lock_cycles_total``, ``mt_lock_long_holds_total``.
"""

from __future__ import annotations

import os
import threading
import time

# guards enable/reset + graph writes.  RLock, NOT Lock: recording runs
# inside arbitrary acquire/release paths, and an allocation under it
# can trigger cyclic GC whose finalizers (memgov Charge.__del__ —
# see MemoryGovernor._mu's comment) acquire a TracedLock on the SAME
# thread, re-entering the recorder; a plain Lock would self-deadlock.
_STATE_MU = threading.RLock()
_enabled = os.environ.get("MT_LOCK_TRACE", "") not in ("", "0", "off")

try:
    LONG_HOLD_S = float(os.environ.get("MT_LOCK_TRACE_LONG_HOLD_S",
                                       "0.5"))
except ValueError:
    LONG_HOLD_S = 0.5

# name-keyed order graph: (held_name, acquired_name) -> count, plus a
# witness site (thread name at first observation) per direction
_edges: dict[tuple[str, str], int] = {}
_edge_witness: dict[tuple[str, str], str] = {}
_self_nests: dict[str, int] = {}
# long holds: (name, seconds, thread) tuples, bounded
_long_holds: list[tuple[str, float, str]] = []
_MAX_LONG_HOLDS = 256
# total traced acquisitions (proof the trace actually saw the plane —
# an all-green acyclicity assertion over zero acquisitions is vacuous)
_acquires = 0

_local = threading.local()


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn tracing on for locks constructed FROM NOW ON (factories
    decide at construction; import-time singletons keep plain locks
    unless ``MT_LOCK_TRACE`` was set at process start)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop the recorded graph (between test scenarios)."""
    global _acquires
    with _STATE_MU:
        _edges.clear()
        _edge_witness.clear()
        _self_nests.clear()
        del _long_holds[:]
        _acquires = 0


def acquire_count() -> int:
    return _acquires


def _held_stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


class TracedLock:
    """A named Lock/RLock recording acquisition order and hold times.

    Drop-in for ``threading.Lock``/``RLock`` (context manager,
    ``acquire(blocking, timeout)``, ``release``, ``locked``) — also
    accepted by ``threading.Condition(lock=...)``."""

    __slots__ = ("name", "_inner", "_reentrant", "_waiters",
                 "_acquired_at", "_contended")

    def __init__(self, name: str, *, rlock: bool = False):
        self.name = name
        self._inner = threading.RLock() if rlock else threading.Lock()
        self._reentrant = rlock
        self._waiters = 0          # racy int under the GIL: a hint
        self._acquired_at = 0.0
        self._contended = False

    # -- acquisition bookkeeping -------------------------------------------

    def _note_acquired(self, reentry: bool) -> None:
        global _acquires
        _acquires += 1          # racy int under the GIL: a lower bound
        stack = _held_stack()
        if not reentry:
            seen = set()
            for held in stack:
                hn = held.name
                if hn in seen:
                    continue
                seen.add(hn)
                if hn == self.name:
                    with _STATE_MU:
                        _self_nests[hn] = _self_nests.get(hn, 0) + 1
                    continue
                key = (hn, self.name)
                with _STATE_MU:
                    _edges[key] = _edges.get(key, 0) + 1
                    if key not in _edge_witness:
                        _edge_witness[key] = \
                            threading.current_thread().name
        stack.append(self)
        self._acquired_at = time.monotonic()

    def _note_released(self) -> None:
        stack = _held_stack()
        # pop the most recent entry for self (release order may not be
        # strictly LIFO across locks)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        held_for = time.monotonic() - self._acquired_at
        if held_for >= LONG_HOLD_S and (self._contended or
                                        self._waiters > 0):
            with _STATE_MU:
                if len(_long_holds) < _MAX_LONG_HOLDS:
                    _long_holds.append(
                        (self.name, held_for,
                         threading.current_thread().name))
        self._contended = False

    # -- lock protocol ------------------------------------------------------

    def _depths(self) -> dict:
        d = getattr(_local, "depth", None)
        if d is None:
            d = _local.depth = {}
        return d

    def acquire(self, blocking: bool = True, timeout: float = -1):
        depths = self._depths()
        if self._reentrant and depths.get(id(self), 0) > 0:
            # re-entry on a lock this thread already owns: no new
            # ordering information, just deepen
            got = self._inner.acquire(blocking, timeout)
            if got:
                depths[id(self)] += 1
            return got
        contended = self._waiters > 0 or self._inner_locked()
        self._waiters += 1
        try:
            got = self._inner.acquire(blocking, timeout)
        finally:
            self._waiters -= 1
        if got:
            self._contended = contended
            self._note_acquired(reentry=False)
            if self._reentrant:
                depths[id(self)] = 1
        return got

    def release(self) -> None:
        if self._reentrant:
            depths = self._depths()
            d = depths.get(id(self), 0)
            if d > 1:
                depths[id(self)] = d - 1
                self._inner.release()
                return
            depths.pop(id(self), None)
        self._note_released()
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner_locked()

    def _inner_locked(self) -> bool:
        inner = self._inner
        if hasattr(inner, "locked"):
            return inner.locked()
        # RLock on 3.10 has no locked(); owned-by-anyone approximation
        if inner.acquire(False):
            inner.release()
            return False
        return True

    # Condition(lock=...) integration: delegate the save/restore hooks
    # so cond.wait() keeps the order stack balanced
    def _release_save(self):
        self._note_released()
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._note_acquired(reentry=False)

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return any(l is self for l in _held_stack())

    def __repr__(self) -> str:
        return f"<TracedLock {self.name!r}>"


def mtlock(name: str):
    """A mutex for the threaded data plane: plain ``threading.Lock``
    when tracing is off (zero overhead), a named :class:`TracedLock`
    when on."""
    if _enabled:
        return TracedLock(name)
    return threading.Lock()


def mtrlock(name: str):
    """Reentrant variant of :func:`mtlock`."""
    if _enabled:
        return TracedLock(name, rlock=True)
    return threading.RLock()


# -- graph queries -----------------------------------------------------------


def snapshot() -> dict:
    """{edges: {(a,b): count}, self_nests, long_holds} — a consistent
    copy for assertions and the scrape."""
    with _STATE_MU:
        return {"edges": dict(_edges),
                "witness": dict(_edge_witness),
                "self_nests": dict(_self_nests),
                "long_holds": list(_long_holds)}


def cycles() -> list[list[str]]:
    """Strongly connected components with more than one lock in the
    recorded order graph — each is a potential AB/BA deadlock."""
    with _STATE_MU:
        adj: dict[str, set[str]] = {}
        for (a, b) in _edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
    # iterative Tarjan SCC
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]
    for root in adj:
        if root in index:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))
    return out


def long_holds() -> list[tuple[str, float, str]]:
    with _STATE_MU:
        return list(_long_holds)


def assert_acyclic(allow_long_holds: bool = False) -> dict:
    """Raise AssertionError naming each cycle's locks and witness
    edges if the recorded order graph has one; returns the snapshot
    (edge count, long holds) when clean."""
    snap = snapshot()
    cyc = cycles()
    if cyc:
        detail = []
        for comp in cyc:
            members = set(comp)
            witnesses = [
                f"{a}->{b} (x{n}, first by {snap['witness'][(a, b)]})"
                for (a, b), n in sorted(snap["edges"].items())
                if a in members and b in members]
            detail.append(f"cycle {comp}: {'; '.join(witnesses)}")
        raise AssertionError(
            "lock-order cycles (potential AB/BA deadlock): "
            + " | ".join(detail))
    if not allow_long_holds and snap["long_holds"]:
        worst = max(snap["long_holds"], key=lambda h: h[1])
        raise AssertionError(
            f"{len(snap['long_holds'])} long lock holds under "
            f"contention (worst: {worst[0]} held {worst[1]:.3f}s by "
            f"{worst[2]}; threshold {LONG_HOLD_S}s)")
    return {"edges": len(snap["edges"]),
            "self_nests": sum(snap["self_nests"].values()),
            "long_holds": len(snap["long_holds"])}


def render_metrics() -> list[str]:
    """``mt_lock_*`` exposition lines (admin/metrics.py calls this at
    scrape time).  Idle contract: tracing off AND nothing recorded =>
    no families at all."""
    snap = snapshot()
    if not _enabled and not snap["edges"] and not snap["long_holds"]:
        return []
    if not snap["edges"] and not snap["long_holds"] and \
            not snap["self_nests"]:
        return []
    return [
        "# TYPE mt_lock_order_edges_total counter",
        f"mt_lock_order_edges_total {len(snap['edges'])}",
        "# TYPE mt_lock_cycles_total counter",
        f"mt_lock_cycles_total {len(cycles())}",
        "# TYPE mt_lock_long_holds_total counter",
        f"mt_lock_long_holds_total {len(snap['long_holds'])}",
    ]
