"""Bucket DNS federation (cmd/etcd.go + pkg/dns/etcd_dns.go).

The reference federates multiple clusters under one domain by writing
CoreDNS SRV records into etcd on MakeBucket and deleting them on
DeleteBucket; a request for a bucket homed on another cluster resolves
through DNS.  Here the record store is pluggable:

  * FileDNSStore — a shared JSON file with advisory locking: the
    zero-egress stand-in for etcd that still coordinates multiple
    server processes on one host/NFS mount (tests and local
    federations use this);
  * EtcdDNSStore — real etcd records through the v3 JSON gateway
    (utils/etcd.py) in the CoreDNS/skydns key layout, with an atomic
    create transaction guarding bucket-name races.

FederationSys wires a store to a server: register/unregister on bucket
create/delete, and `lookup_other` drives a 307 redirect for buckets
homed elsewhere (the reference proxies or redirects the same way).
"""

from __future__ import annotations

import fcntl
import json
import os
import time
from dataclasses import dataclass
from typing import Optional


class DNSError(Exception):
    pass


class BucketTaken(DNSError):
    """Bucket already registered by another cluster in the federation."""


@dataclass
class DNSRecord:
    bucket: str
    host: str
    port: int
    created_ns: int = 0

    def to_dict(self) -> dict:
        return {"bucket": self.bucket, "host": self.host,
                "port": self.port, "created_ns": self.created_ns}

    @classmethod
    def from_dict(cls, d: dict) -> "DNSRecord":
        return cls(d["bucket"], d["host"], int(d["port"]),
                   int(d.get("created_ns", 0)))


class FileDNSStore:
    """Shared-file record store with fcntl locking (etcd stand-in)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)

    def _with_lock(self, fn):
        lock = self.path + ".lock"
        with open(lock, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                try:
                    with open(self.path) as f:
                        table = json.load(f)
                except (OSError, json.JSONDecodeError):
                    table = {}
                out, table2 = fn(table)
                if table2 is not None:
                    tmp = self.path + f".tmp{os.getpid()}"
                    with open(tmp, "w") as f:
                        json.dump(table2, f)
                    os.replace(tmp, self.path)
                return out
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    def put(self, rec: DNSRecord, replace: bool = False) -> None:
        def fn(table):
            cur = table.get(rec.bucket)
            if cur and not replace and \
                    (cur["host"], cur["port"]) != (rec.host, rec.port):
                raise BucketTaken(
                    f"bucket {rec.bucket!r} is owned by "
                    f"{cur['host']}:{cur['port']}")
            table[rec.bucket] = rec.to_dict()
            return None, table
        self._with_lock(fn)

    def get(self, bucket: str) -> Optional[DNSRecord]:
        def fn(table):
            d = table.get(bucket)
            return (DNSRecord.from_dict(d) if d else None), None
        return self._with_lock(fn)

    def delete(self, bucket: str) -> None:
        def fn(table):
            table.pop(bucket, None)
            return None, table
        self._with_lock(fn)

    def list(self) -> list[DNSRecord]:
        def fn(table):
            return [DNSRecord.from_dict(d) for d in table.values()], None
        return self._with_lock(fn)


class EtcdDNSStore:
    """etcd-backed store (pkg/dns/etcd_dns.go) with the CoreDNS/skydns
    key layout: a record for bucket `b` under domain `example.com`
    lives at /skydns/com/example/b — the exact keys CoreDNS's etcd
    plugin serves SRV/A answers from, so a real CoreDNS pointed at the
    same etcd resolves the federation without any extra glue."""

    def __init__(self, endpoints, domain: str):
        from .etcd import EtcdClient
        self._c = EtcdClient(endpoints)
        parts = [p for p in domain.strip(".").split(".") if p]
        self._base = "/skydns/" + "/".join(reversed(parts))

    def _key(self, bucket: str) -> str:
        return f"{self._base}/{bucket}"

    def put(self, rec: DNSRecord, replace: bool = False) -> None:
        # skydns record shape (pkg/dns/etcd_dns.go SrvRecord)
        blob = json.dumps({
            "host": rec.host, "port": rec.port, "ttl": 30,
            "creationDate": rec.created_ns}).encode()
        if replace:
            self._c.put(self._key(rec.bucket), blob)
            return
        # ATOMIC create via etcd txn: two clusters racing MakeBucket on
        # the same name must see exactly one winner (a get-then-put
        # would let both succeed; the reference guards with the same
        # create-revision transaction)
        if self._c.put_if_absent(self._key(rec.bucket), blob):
            return
        existing = self.get(rec.bucket)
        if existing is not None and \
                (existing.host, existing.port) == (rec.host, rec.port):
            return                      # already ours: idempotent
        raise BucketTaken(rec.bucket)

    def get(self, bucket: str) -> Optional[DNSRecord]:
        blob = self._c.get(self._key(bucket))
        if blob is None:
            return None
        d = json.loads(blob)
        return DNSRecord(bucket, d["host"], int(d["port"]),
                         int(d.get("creationDate", 0)))

    def delete(self, bucket: str) -> None:
        self._c.delete(self._key(bucket))

    def list(self) -> list[DNSRecord]:
        out = []
        for k, v in self._c.get_prefix(self._base + "/"):
            bucket = k.decode().rsplit("/", 1)[-1]
            d = json.loads(v)
            out.append(DNSRecord(bucket, d["host"], int(d["port"]),
                                 int(d.get("creationDate", 0))))
        return out


class FederationSys:
    """Per-server federation driver (globalDNSConfig usage)."""

    def __init__(self, store, domain: str, self_host: str,
                 self_port: int):
        self.store = store
        self.domain = domain
        self.self_host = self_host
        self.self_port = self_port

    @classmethod
    def from_config(cls, cfg, host: str,
                    port: int) -> "FederationSys | None":
        if cfg.get("federation", "enable") != "on":
            return None
        # DNS records must carry a ROUTABLE owner address: a wildcard
        # bind would make every cluster look like the owner of every
        # bucket and emit http://0.0.0.0 redirects
        adv = cfg.get("federation", "advertise")
        if adv:
            ahost, _, aport = adv.rpartition(":")
            host, port = ahost or adv, int(aport) if aport else port
        elif host in ("0.0.0.0", "::", ""):
            raise DNSError(
                "federation with a wildcard bind requires "
                "federation.advertise=<host:port>")
        domain = cfg.get("federation", "domain")
        # etcd-backed records (cmd/etcd.go + pkg/dns/etcd_dns.go) when
        # the etcd subsystem is configured; shared-file store otherwise
        try:
            etcd_eps = cfg.get("etcd", "endpoints")
        except KeyError:
            etcd_eps = ""
        if etcd_eps:
            return cls(EtcdDNSStore(etcd_eps, domain), domain,
                       host, port)
        path = cfg.get("federation", "dns_file")
        if not path:
            raise DNSError(
                "federation requires etcd.endpoints or "
                "federation.dns_file")
        return cls(FileDNSStore(path), domain, host, port)

    def _is_self(self, rec: DNSRecord) -> bool:
        return (rec.host, rec.port) == (self.self_host, self.self_port)

    def register(self, bucket: str) -> bool:
        """MakeBucket hook; BucketTaken when owned elsewhere.  Returns
        True when this call created the record (False when the bucket
        was already ours) — a failed local create must roll back only a
        fresh registration."""
        existing = self.store.get(bucket)
        if existing is not None and self._is_self(existing):
            return False
        self.store.put(DNSRecord(bucket, self.self_host, self.self_port,
                                 time.time_ns()))
        return True

    def unregister(self, bucket: str) -> None:
        rec = self.store.get(bucket)
        if rec is not None and self._is_self(rec):
            self.store.delete(bucket)

    def lookup_other(self, bucket: str) -> Optional[DNSRecord]:
        """Record for a bucket homed on ANOTHER cluster, else None."""
        rec = self.store.get(bucket)
        if rec is None or self._is_self(rec):
            return None
        return rec

    def federated_buckets(self) -> list[DNSRecord]:
        return self.store.list()
