"""Reusable framed-buffer pool for the PUT pipeline.

Every streaming batch erasure-encodes into a (k+m, framed_len) uint8
array that is written out and thrown away.  On this class of host the
allocation itself is not the cost — the FIRST TOUCH is: a fresh 6 MB
numpy buffer page-faults ~1.5k times while the encode fills it, the
same first-touch tax the bench measures as ``tmpfs_fresh_write_floor``
for shard files.  Recycling the arrays keeps the pages hot, so batch
N+1 encodes into memory batch N already faulted in.

The pool is keyed by exact array shape (streaming batches are
constant-size, so all but a stream's tail batch hit), bounded in total
bytes, and thread-safe.  ``acquire`` never blocks: a miss allocates
fresh and the bound only limits what ``release`` keeps.  Memory for
the whole pipeline therefore stays O(pipeline_depth x batch): buffers
are released back as each batch's drive writes complete, and the
put loop bounds batches in flight to the ``pipeline.depth`` knob.
"""

from __future__ import annotations


import numpy as np
from .locktrace import mtlock

# total bytes the GLOBAL pool may retain; with 64 MiB stream batches a
# framed buffer is ~85 MiB, so this keeps a handful of batches across
# concurrent streams without growing into a cache of dead shapes
DEFAULT_MAX_BYTES = 512 * (1 << 20)


class BufPool:
    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES):
        self._mu = mtlock("putw.bufpool")
        self._free: dict[tuple, list[np.ndarray]] = {}
        self._held = 0
        self.max_bytes = max_bytes
        self.hits = 0
        self.misses = 0

    def acquire(self, shape: tuple) -> np.ndarray:
        """A uint8 array of ``shape`` — recycled when one is free,
        freshly allocated otherwise (never blocks)."""
        with self._mu:
            lst = self._free.get(shape)
            if lst:
                arr = lst.pop()
                self._held -= arr.nbytes
                self.hits += 1
                return arr
            self.misses += 1
        return np.empty(shape, dtype=np.uint8)

    def release(self, arr: np.ndarray) -> None:
        """Return an array for reuse; silently dropped once the pool
        holds ``max_bytes`` (the GC then reclaims it as before)."""
        if arr is None or arr.dtype != np.uint8 or not arr.flags.owndata:
            return
        with self._mu:
            if self._held + arr.nbytes > self.max_bytes:
                return
            self._free.setdefault(arr.shape, []).append(arr)
            self._held += arr.nbytes

    def held_bytes(self) -> int:
        with self._mu:
            return self._held

    def clear(self) -> None:
        with self._mu:
            self._free.clear()
            self._held = 0


# process-wide pool shared by every erasure layer's put pipeline
GLOBAL = BufPool()
