"""Shared small utilities."""

from __future__ import annotations


def close_quietly(it) -> None:
    """Close an iterator/generator if it supports close(), swallowing
    teardown errors — the one definition of the finally-block every
    streaming pipeline stage (readers, decompressors, re-chunkers)
    uses to propagate early termination to its source."""
    close = getattr(it, "close", None)
    if close is not None:
        try:
            close()
        except Exception:  # noqa: BLE001 — source already failing
            pass
