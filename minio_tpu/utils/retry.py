"""Shared retry policy — jittered exponential backoff with a retry
budget (cmd/rest retry + the gRPC retry-throttling token bucket).

One policy object is shared by every caller on a transport (an RPC
client, a gateway wire client): the *budget* is what keeps a cluster-
wide incident from turning into a retry storm — when most requests are
failing, the bucket drains and retries stop, so the recovering peer
sees offered load, not offered load times attempts.

Everything nondeterministic is injectable (``rng``, ``sleep``) so the
chaos tier can drive the policy with a seeded generator and a recording
sleep — no wall-clock races in tests.
"""

from __future__ import annotations

import random
import threading
import time


class RetryBudget:
    """Token-bucket retry throttle (the gRPC retryThrottling analog):
    each retry spends one token, each SUCCESS refunds ``refund`` tokens
    (capped).  When the bucket cannot cover a whole token, retries are
    refused — first-attempt traffic always passes, only the multiplier
    is shed."""

    def __init__(self, capacity: float = 10.0, refund: float = 0.5):
        self.capacity = float(capacity)
        self.refund = float(refund)
        self._tokens = float(capacity)
        self._mu = threading.Lock()

    def try_spend(self) -> bool:
        with self._mu:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def credit(self) -> None:
        with self._mu:
            self._tokens = min(self.capacity, self._tokens + self.refund)

    @property
    def tokens(self) -> float:
        with self._mu:
            return self._tokens


class RetryPolicy:
    """Jittered exponential backoff, idempotent-only, budget-capped.

    ``attempts`` counts the FIRST try: attempts=3 means at most two
    retries.  Backoff uses full jitter (uniform over [0, min(cap,
    base * 2^retry)]) so synchronized clients spread out instead of
    retrying in lockstep against a struggling peer.
    """

    def __init__(self, attempts: int = 3, base_s: float = 0.05,
                 cap_s: float = 2.0, budget: RetryBudget | None = None,
                 rng: random.Random | None = None, sleep=time.sleep):
        self.attempts = max(1, int(attempts))
        self.base_s = base_s
        self.cap_s = cap_s
        self.budget = budget
        self.rng = rng or random.Random()
        self.sleep = sleep

    def backoff_s(self, retry_nr: int) -> float:
        """Jittered delay before retry number ``retry_nr`` (0-based)."""
        return self.rng.uniform(
            0.0, min(self.cap_s, self.base_s * (2 ** retry_nr)))

    def may_retry(self, attempt: int, idempotent: bool) -> bool:
        """attempt is 0-based (0 = the first try just failed).  Only
        idempotent work may be replayed — the request may already have
        executed on the far side — and only while the budget holds."""
        if attempt + 1 >= self.attempts:
            return False
        if not idempotent:
            return False
        if self.budget is not None and not self.budget.try_spend():
            return False
        return True

    def wait(self, attempt: int) -> None:
        self.sleep(self.backoff_s(attempt))

    def on_success(self) -> None:
        if self.budget is not None:
            self.budget.credit()
