"""Shared build-on-demand loader for the native (C/C++) helper libraries.

Three subsystems carry native kernels — snappy compression
(native/snappy.cc), HighwayHash (hashing/native/highwayhash.c) and the
GF(2^8) erasure matmul (native/gf8.cc) — the roles the reference fills
with assembly-accelerated Go modules (SURVEY.md §2.4).  They all share
one loading discipline, implemented once here:

* rebuild when the .so is missing or older than the source;
* compile to a temp file and os.replace it (atomic under concurrent
  processes);
* honor MT_NATIVE=0 (force the pure-Python fallbacks) and CC;
* never raise: a missing compiler returns None and callers fall back.

Thread-safe: a per-path lock guarantees a library is built and loaded
exactly once, and concurrent first callers WAIT for the build instead of
silently taking the slow path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_meta_lock = threading.Lock()
_locks: dict[str, threading.Lock] = {}
_cache: dict[str, ctypes.CDLL | None] = {}


def load(src: str, so: str, timeout: int = 120) -> ctypes.CDLL | None:
    """Build (if stale) and load `src` into `so`; None when unavailable.

    Idempotent per `so` path; concurrent callers of the SAME library
    block until the first build finishes rather than observing a
    half-initialized state — a slow compile of one library never stalls
    loads of the others."""
    # sanitizer/CI hook: MT_NATIVE_BUILD_DIR redirects the compiled .so
    # (so an instrumented build never clobbers the production cache)
    # and MT_NATIVE_CFLAGS appends flags, e.g.
    # "-fsanitize=address,undefined" (tests/test_sanitizers.py tier,
    # the buildscripts/race.sh role)
    build_dir = os.environ.get("MT_NATIVE_BUILD_DIR", "")
    if build_dir:
        so = os.path.join(build_dir, os.path.basename(so))
    extra = os.environ.get("MT_NATIVE_CFLAGS", "").split()
    with _meta_lock:
        lock = _locks.setdefault(so, threading.Lock())
    with lock:
        if so in _cache:
            return _cache[so]
        lib = None
        if os.environ.get("MT_NATIVE", "1") != "0":
            try:
                if not os.path.exists(so) or (
                        os.path.getmtime(so) < os.path.getmtime(src)):
                    os.makedirs(os.path.dirname(so), exist_ok=True)
                    tmp = so + f".tmp{os.getpid()}"
                    cc = os.environ.get("CC", "g++" if src.endswith(
                        (".cc", ".cpp")) else "cc")
                    subprocess.run(  # mt-lint: ok(lock-discipline) one-time lazy build: waiters NEED the .so this compile produces; double-checked via _cache so it runs once per process
                        [cc, "-O3", "-shared", "-fPIC", *extra,
                         "-o", tmp, src],
                        check=True, capture_output=True, timeout=timeout)
                    os.replace(tmp, so)
                lib = ctypes.CDLL(so)
            except Exception:  # noqa: BLE001 — fallback path is Python
                lib = None
        _cache[so] = lib
        return lib
