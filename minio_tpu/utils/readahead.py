"""Bounded readahead over an iterator — the async-I/O overlap layer.

Role of klauspost/readahead in the reference (go.mod:39, used at
cmd/xl-storage.go:1544-1546 for big CreateFile streams) and of the
io.Pipe overlap in the bitrot writers (cmd/bitrot-streaming.go:74-89):
production (disk reads + erasure decode, or network reads) runs in a
background thread up to `depth` items ahead of the consumer, so block
batch N+1's I/O overlaps block N's send — the double-buffered pipeline
of SURVEY.md §2.3 on the host side.

Semantics:
  * order-preserving, exceptions re-raised at the consumer's position;
  * bounded queue: the producer blocks once `depth` items are pending
    (memory stays O(depth x item));
  * close() (or GC, or generator .close() from an abandoned for-loop)
    stops the producer promptly — a disconnected HTTP client must not
    leave a thread streaming a 5 TiB object into a queue.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

_SENTINEL = object()


class Readahead:
    def __init__(self, it: Iterable, depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._closed = threading.Event()
        # carry the caller's request context into the producer thread
        # (the erasure fan-out discipline): storage spans keep their
        # request ID and the X-ray clock still receives drive_read/
        # decode attribution (as async detail — production overlaps
        # the consumer by design)
        from ..obs import stages as _stages
        from ..obs import trace as _trace
        self._rid = _trace.get_request_id()
        self._clock = _stages.current()
        self._thread = threading.Thread(
            target=self._produce, args=(iter(it),), daemon=True,
            name="mt-readahead")
        self._thread.start()

    def _produce(self, it: Iterator) -> None:
        from ..obs import stages as _stages
        from ..obs import trace as _trace
        _trace.set_request_id(self._rid)
        _stages.set_clock(self._clock)
        try:
            for item in it:
                while not self._closed.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._closed.is_set():
                    break
            else:
                self._put_forever((_SENTINEL, None))
                return
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            self._put_forever((_SENTINEL, e))
            return
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — source close is
                    pass           # best-effort on the way down

    def _put_forever(self, item) -> None:
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed.is_set():
            raise StopIteration
        item = self._q.get()
        if isinstance(item, tuple) and len(item) == 2 and \
                item[0] is _SENTINEL:
            self._closed.set()
            if item[1] is not None:
                raise item[1]
            raise StopIteration
        return item

    def close(self, _empty=queue.Empty) -> None:
        # _empty bound at def time: __del__ may run during interpreter
        # shutdown after module globals are cleared
        self._closed.set()
        # drain so a blocked producer sees the flag promptly
        try:
            while True:
                self._q.get_nowait()
        except _empty:
            pass
        # JOIN before returning: the producer may be mid-read on a
        # shared source (the HTTP body socket) — the caller must not
        # resume using that source while our thread still reads it.
        # Bounded: after the in-flight read the flag stops the loop.
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=60)

    def __del__(self):  # abandoned mid-stream (client disconnect)
        self.close()


def readahead(it: Iterable, depth: int = 2) -> Readahead:
    """Wrap `it` so it is produced `depth` items ahead in a thread."""
    return Readahead(it, depth)
