"""In-memory non-blocking pub/sub (pkg/pubsub/pubsub.go).

Publish never blocks: a subscriber that cannot keep up drops messages
(pkg/pubsub/pubsub.go:37-39 writes into a select with default).  Used by
event notification (ListenNotification), HTTP tracing, and the console
log ring.
"""

from __future__ import annotations

import queue
import time
from typing import Any, Callable, Iterator, Optional
from .locktrace import mtlock


class PubSub:
    def __init__(self, max_queue: int = 1000):
        self._subs: list[tuple[queue.Queue, Optional[Callable]]] = []
        self._mu = mtlock("obs.pubsub")
        self._max_queue = max_queue
        self._ring = None                 # seq-numbered tail for peer polls
        self._ring_until = 0.0
        self._seq = 0
        # plain-int mirror of len(self._subs): hot paths gate span
        # construction on ``active`` and must not take the lock (or
        # allocate) just to learn nobody is listening
        self._n_subs = 0

    def enable_ring(self, size: int = 2000) -> None:
        """Keep a sequence-numbered tail of published items so remote
        peers can poll increments (peerRESTMethodTrace aggregation).
        The ring only captures while a poller is ACTIVE (a since() call
        in the last 10s) so idle clusters pay nothing on the hot path."""
        from collections import deque
        with self._mu:
            if self._ring is None:
                self._ring = deque(maxlen=size)
                self._ring_until = 0.0

    @property
    def ring_enabled(self) -> bool:
        return self._ring is not None

    @property
    def ring_active(self) -> bool:
        return self._ring is not None and \
            time.monotonic() < self._ring_until

    @property
    def active(self) -> bool:
        """True when publishing could reach anyone: a live subscriber or
        a recently-polled ring.  Lock-free single predicate — THE guard
        instrumented hot paths check before building a span dict."""
        if self._n_subs:
            return True
        until = self._ring_until
        if not until:
            return False
        return time.monotonic() < until

    def since(self, seq: int, limit: int = 500) -> tuple[int, list]:
        """Items published after ``seq``; returns (cursor, items) where
        cursor is the seq of the LAST RETURNED item (not the global
        latest — a truncated read must not skip buffered items).
        limit=0 returns the current latest seq with no items (cursor
        priming for live streams).  Calling this keeps the ring
        capturing for another 10s."""
        with self._mu:
            if self._ring is None:
                return self._seq, []
            self._ring_until = time.monotonic() + 10.0
            if limit == 0 or seq > self._seq:
                # limit=0 primes; seq ahead of the stream means the
                # caller's cursor is from a previous process life —
                # report the current head so it re-primes
                return self._seq, []
            out = []
            last = seq
            for s, i in self._ring:
                if s > seq:
                    out.append(i)
                    last = s
                    if len(out) >= limit:
                        break
            return last, out

    def publish(self, item: Any) -> None:
        with self._mu:
            subs = list(self._subs)
            if self._ring is not None and \
                    time.monotonic() < self._ring_until:
                self._seq += 1
                self._ring.append((self._seq, item))
        for q, flt in subs:
            if flt is not None:
                try:
                    if not flt(item):
                        continue
                except Exception:  # noqa: BLE001 — a broken subscriber
                    continue       # filter must never fail the
                                   # publisher (publish now runs inside
                                   # storage/RPC data-path finallys)
            try:
                q.put_nowait(item)
            except queue.Full:
                pass                      # slow subscriber: drop, not block

    def subscribe(self, filter_fn: Optional[Callable] = None
                  ) -> "Subscription":
        q: queue.Queue = queue.Queue(self._max_queue)
        sub = Subscription(self, q)
        with self._mu:
            self._subs.append((q, filter_fn))
            self._n_subs = len(self._subs)
        return sub

    def _unsubscribe(self, q: queue.Queue) -> None:
        with self._mu:
            self._subs = [(qq, f) for qq, f in self._subs if qq is not q]
            self._n_subs = len(self._subs)

    @property
    def num_subscribers(self) -> int:
        with self._mu:
            return len(self._subs)


class Subscription:
    def __init__(self, ps: PubSub, q: queue.Queue):
        self._ps = ps
        self._q = q
        self.closed = False

    def get(self, timeout: Optional[float] = None) -> Any:
        """Next item or None on timeout."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def drain(self, max_items: int, timeout: float) -> Iterator[Any]:
        deadline = time.monotonic() + timeout
        n = 0
        while n < max_items:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            item = self.get(timeout=remaining)
            if item is None:
                return
            yield item
            n += 1

    def close(self) -> None:
        if not self.closed:
            self._ps._unsubscribe(self._q)
            self.closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
