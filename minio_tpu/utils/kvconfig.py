"""Config subsystem — KVS registry with env overrides
(cmd/config/config.go:209,302; pkg/env).

``Config`` is {subsystem: {key: value}} with registered defaults + help.
Every key is overridable by environment variable ``MT_<SUBSYS>_<KEY>``
(the reference's MINIO_<SUBSYS>_<KEY>).  Dynamic updates go through
``set``/``get`` (admin SetConfigKV analog) and persist as JSON in the
system volume when bound to an object layer.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from .locktrace import mtlock

ENV_PREFIX = "MT"


@dataclass
class HelpKV:
    key: str
    description: str = ""
    optional: bool = True
    type: str = "string"


@dataclass
class SubsysSpec:
    name: str
    defaults: dict[str, str] = field(default_factory=dict)
    help: list[HelpKV] = field(default_factory=list)


_REGISTRY: dict[str, SubsysSpec] = {}


def register_subsys(name: str, defaults: dict[str, str],
                    help_kvs: list[HelpKV] | None = None) -> None:
    _REGISTRY[name] = SubsysSpec(name, dict(defaults), help_kvs or [])


def parse_duration(s: str, default: float = 10.0) -> float:
    """'10s' / '2m' / '500ms' -> seconds (cmd/config duration keys)."""
    s = (s or "").strip()
    try:
        if s.endswith("ms"):
            return float(s[:-2]) / 1000.0
        if s.endswith("s"):
            return float(s[:-1])
        if s.endswith("m"):
            return float(s[:-1]) * 60.0
        return float(s)
    except ValueError:
        return default


# built-in subsystems (subset of the reference's 15+, grows with features)
register_subsys("api", {
    "requests_max": "0",            # 0 = auto
    "requests_deadline": "10s",
    # load shedding: waiters beyond this queue depth are shed with
    # 503 + Retry-After immediately instead of parking a thread
    # (0 = auto: 2x requests_max)
    "requests_queue": "0",
    # per-connection deadlines (cmd/http/server.go:185 analog): socket
    # timeout while reading the request line/headers and between
    # keep-alive requests, and the budget for reading one request
    # body — a slowloris trickling bytes resets per-recv timeouts but
    # cannot outlive the body budget.  The budget scales with the
    # declared size so large legitimate uploads are never cut while
    # making progress: body_deadline + Content-Length / body_min_rate
    # (bytes/sec; 0 disables the scaling term)
    "read_header_timeout": "30s",
    "body_deadline": "2m",
    "body_min_rate": "1048576",     # 1 MiB/s floor rate
    # graceful shutdown drain (s3/server.py stop): the listener closes
    # first (new connections refused), then in-flight requests get this
    # long to finish before remaining connections are severed; idle
    # keep-alive connections are severed immediately.  0 restores the
    # immediate-sever behavior.  Live-reloadable (reload_api_config).
    "shutdown_drain_s": "5s",
    "cors_allow_origin": "*",
    # node memory governor (utils/memgov.py): memory-hungry request
    # paths (Select scanners, listing walks, multipart assembly) charge
    # bounded working-set estimates; a charge pushing the node past
    # ``mem_limit`` is shed with 503 + Retry-After (``mem_retry_after``)
    # instead of allocating toward an OOM.  0 disables admission
    # (charges stay accounted for the mt_mem_* scrape families).
    # Sizes accept 268435456 / 256MiB / 1GiB.  Live-reloadable
    # (reload_api_config on admin SetConfigKV).
    "mem_limit": "0",
    "mem_retry_after": "1s",
    # streaming S3 Select scanner block (s3select/__init__.py): decoded
    # object bytes are pulled and scanned this many bytes at a time;
    # peak Select memory is O(a few blocks) regardless of object size
    "select_block_bytes": "1048576",
})
register_subsys("rpc", {
    # node-level circuit breaker (parallel/rpc.py CircuitBreaker):
    # consecutive transport failures before the peer opens, and how
    # long it stays open before a half-open probe is admitted
    "breaker_failures": "3",
    "breaker_cooldown": "3s",
    # shared jittered-exponential retry policy (utils/retry.py):
    # total attempts (first try included), backoff base/cap, and the
    # retry-budget bucket capacity (0 disables the budget)
    "retry_attempts": "3",
    "retry_base": "50ms",
    "retry_cap": "2s",
    "retry_budget": "10",
    # chunked internode streaming (parallel/rpc.py framed raw mode +
    # storage/remote.py): bulk shard bodies larger than
    # ``stream_chunk_bytes`` ride one POST as length-prefixed frames
    # the peer applies to the drive AS THEY LAND (and streamed raw
    # responses are read chunk-at-a-time), so per-connection memory is
    # O(chunk) instead of O(shard).  ``stream_enable=off`` restores
    # whole-body raw calls.  Live-reloadable (S3Server.reload_rpc_config
    # on admin SetConfigKV).
    "stream_enable": "on",
    "stream_chunk_bytes": "1048576",
})
register_subsys("drive", {  # mt-lint: ok(kvconfig-drift) read per scrape (storage/health.py slow_drives) — SetConfigKV lands at the very next scrape, no reload hook needed
    # slow-drive detection over the last-minute latency windows
    # (obs/lastminute.py + storage/health.py slow_drives): a drive
    # whose p50 exceeds multiple x the set median is flagged in
    # health/metrics (mt_node_disk_slow), never ejected.  Read at
    # scrape time, so admin SetConfigKV retunes detection live.
    "slow_latency_multiple": "4",
    "slow_min_samples": "10",
})
register_subsys("pipeline", {
    # pipelined PUT data plane (storage/writers.py + the put loops in
    # objectlayer/erasure_object.py): ``depth`` bounds encoded batches
    # in flight per stream (framed buffers + md5 chain + readahead —
    # memory stays O(depth x batch); 0 disables the pipeline and
    # restores the serial per-batch fan-out), ``queue_depth`` bounds
    # each drive's writer queue (enqueue blocks at the bound).  Both
    # are read live: admin SetConfigKV retunes a running server.
    # ``md5_lanes`` bounds the native multi-lane MD5 scheduler
    # (hashing/md5fast.py): concurrent streams'/parts' ETag updates
    # coalesce into one N-lane multi-buffer call; 1 pins every stream
    # to the plain single-stream core.
    # ``md5_backend`` picks the strict-ETag engine: auto (measured
    # device-vs-host choice), device (batched accelerator MD5 via the
    # md5 combining bucket, hashing/md5_device.py), native (md5mb.cc
    # lanes), hashlib.  MT_MD5=hashlib still outranks everything (the
    # operator kill switch).  Live-reloadable via SetConfigKV.
    # ``mesh_batch_bytes`` caps the mesh-scaled stream batch: on a
    # mesh codec one huge object's per-dispatch stripe batch grows
    # with the device count (so a single 5 TiB PUT/GET saturates the
    # whole stripe axis, not one chip) up to this many bytes — memory
    # per stream stays O(depth x batch).
    "depth": "2",
    "queue_depth": "2",
    "md5_lanes": "4",
    "md5_backend": "auto",
    "mesh_batch_bytes": "268435456",
})
register_subsys("codec", {
    # cross-request batching codec service (parallel/batcher.py):
    # concurrent encode/decode/reconstruct dispatches on DEVICE
    # backends (tpu/mesh; the numpy host path has no launch cost to
    # amortize and bypasses the batcher) bucket by geometry and
    # coalesce within ``batch_window_us`` into one padded
    # device dispatch (bounded by ``max_batch_blocks`` erasure blocks);
    # ``queue_depth`` bounds queued blocks per bucket — overflow sheds
    # to the serial path.  ``enable=off`` restores per-request
    # dispatches (the serial reference semantics).  Live-reloadable
    # (S3Server.reload_codec_config on admin SetConfigKV).
    "enable": "on",
    "batch_window_us": "200",
    "max_batch_blocks": "256",
    "queue_depth": "1024",
})
register_subsys("commit", {
    # per-drive group-commit plane (storage/commit.py): concurrent
    # streams' create/fsync/rename ops on one _DriveWriter coalesce
    # into batched group commits — one fsync (file + parent dir)
    # settles many streams, durability still acked per stream only
    # after its covering fsync.  ``group_window_us`` lets a drained
    # writer linger for late joiners (0 = batch only what's already
    # queued); ``max_batch`` caps ops per group.  ``pack_threshold``
    # is the small-object packing ceiling: shards past the inline
    # band but at most this many framed bytes append to the drive's
    # journaled segment file instead of their own part file (one
    # fsync covers many objects); ``segment_max_bytes`` rotates the
    # segment.  ``enable=off`` restores the eager per-op fsync path
    # byte-for-byte.  Live-reloadable (S3Server.reload_commit_config
    # on admin SetConfigKV).
    "enable": "on",
    "group_window_us": "0",
    "max_batch": "16",
    "pack_threshold": "1048576",
    "segment_max_bytes": "67108864",
})
register_subsys("cache", {
    # hot-read plane (objectlayer/hotread.py): single-flight GET
    # coalescing + the cluster-coherent hot-object cache.  ``enable``
    # gates the whole plane; ``max_bytes`` bounds cached plain bytes
    # per erasure set (charged to the memory governor under the
    # ``cache`` kind); ``heat_threshold`` is the admission gate —
    # per-key GETs within the last minute (and the server's last-minute
    # GetObject rate) must reach it before a window is cached
    # (coalesced and inline-tiny reads admit immediately);
    # ``singleflight_queue`` bounds waiters parked on one in-flight
    # read — arrivals past it shed to an independent read;
    # ``window_bytes`` is the coalescing/cache granule: requests inside
    # one window share one drive read + decode.  Live-reloadable
    # (S3Server.reload_cache_config on admin SetConfigKV).
    # ``validate_ttl_ms``: sequential cache hits within this window
    # reuse the last quorum validation (generation-fenced: any write
    # commit or peer eviction voids the reuse instantly); 0 = every
    # hit pays its own quorum metadata read
    "enable": "on",
    "max_bytes": "134217728",
    "heat_threshold": "2",
    "singleflight_queue": "64",
    "window_bytes": "8388608",
    "validate_ttl_ms": "50",
})
register_subsys("forensic", {
    # SLO-breach forensic bundles (obs/forensic.py): the trigger
    # engine watches breach-shaped signals and snapshots the flight-
    # recorder rings + live scrape + redacted config into a zip under
    # ``dir`` (default: <first local drive>/.minio-tpu.sys/forensics),
    # reaped oldest-first to ``max_bundles``/``max_bytes``.
    # ``triggers`` is a csv subset of error_ceiling, breaker_burst,
    # shed_burst, slow_drive, heal_backlog; each trigger fires at most
    # once per ``cooldown``.  The error ceiling crosses when 5xx
    # responses reach ``error_rate`` of at least ``error_min_samples``
    # requests inside ``window``.
    "enable": "on",
    "dir": "",
    "max_bundles": "8",
    "max_bytes": "64MiB",
    "cooldown": "60s",
    "triggers": "error_ceiling",
    "error_rate": "0.5",
    "error_min_samples": "100",
    "window": "10s",
    "breaker_burst": "10",
    "shed_burst": "50",
    "backlog_growth": "500",
})
register_subsys("watchdog", {
    # SLO watchdog plane (obs/history.py sampler + obs/watchdog.py
    # rules): ``enable=on`` starts the mt-obs-history sampler, which
    # snapshots selected ``mt_*`` families into bounded multi-
    # resolution rings every ``interval`` and evaluates the rule
    # catalog (obs/watchdog.py RULE_NAMES) each tick.  ``rules`` is a
    # csv subset of the catalog (empty = all); the burn-rate pair
    # fires when the observed error rate burns the ``slo_objective``
    # budget ``burn_{fast,slow}_factor`` times too fast over the
    # matching window; ``drift_z`` is the robust (EWMA + MAD) z-score
    # at which a drive raises drive_degrading.  An alert needs
    # ``pending_for`` consecutive breached evaluations to fire and a
    # re-fire of the same alert is suppressed for ``cooldown`` after
    # it resolves.  ``forensic_rules`` names rules whose firing also
    # invokes the forensic trigger engine (rule name as trigger);
    # ``families`` adds extra sampled family prefixes beyond the
    # built-in selection.  Live-reloadable
    # (S3Server.reload_watchdog_config on admin SetConfigKV; a reload
    # rebuilds the plane, so history rings reset).
    "enable": "off",
    "interval": "10s",
    "families": "",
    "rules": "",
    "slo_objective": "0.01",
    "burn_fast_window": "5m",
    "burn_slow_window": "1h",
    "burn_fast_factor": "14",
    "burn_slow_factor": "6",
    "burn_min_rps": "1",
    "drift_z": "3.5",
    "drift_alpha": "0.3",
    "drift_floor": "1ms",
    "flap_threshold": "6",
    "deadletter_growth": "10",
    "stall_window": "5m",
    "days_to_full": "7",
    # tenant rules (workload attribution plane, obs/metering.py):
    # tenant_burn pages when one access key's error rate burns the
    # slo_objective budget ``tenant_burn_factor`` times too fast over
    # the fast window (given >= tenant_min_rps); noisy_neighbor pages
    # the tenant moving >= ``noisy_share`` of all metered bytes while
    # at least ``noisy_min_tenants`` tenants are active and total
    # traffic exceeds ``noisy_min_bps`` bytes/s.  Both need
    # metering.enable=on to see any mt_tenant_* series at all.
    "tenant_burn_factor": "6",
    "tenant_min_rps": "1",
    "noisy_share": "0.5",
    "noisy_min_tenants": "2",
    "noisy_min_bps": "1000000",
    "pending_for": "2",
    "cooldown": "5m",
    "forensic_rules": "",
})
register_subsys("quota", {  # mt-lint: ok(kvconfig-drift) read per write admission (s3/handlers_object.py _check_quota) — SetConfigKV applies to the very next PUT, no reload hook needed
    # hard bucket quotas (bucket/quota.py + handlers_object.py
    # _check_quota): the per-bucket limit itself is set via the admin
    # set-bucket-quota route; this subsystem is the cluster-wide
    # enforcement switch.  With enable=on a PUT / part upload /
    # multipart complete that would push a bucket past its configured
    # hard quota is rejected with XMinioAdminBucketQuotaExceeded (403)
    # BEFORE any drive fan-out, charged against the crawler usage
    # snapshot plus the in-flight byte delta (background/crawler.py
    # UsageCache).  enable=off keeps quota configs readable but stops
    # enforcing them.
    "enable": "on",
})
register_subsys("storage_class", {  # mt-lint: ok(kvconfig-drift) read per PUT (handlers_object.py) — validated at SetConfigKV time, applies to the next request
    "standard": "",                 # e.g. EC:4
    "rrs": "EC:2",
})
register_subsys("tls", {  # mt-lint: ok(kvconfig-drift) construction-time (secure/certs.py from_config at listener boot) — the PORT cannot switch schemes under a bound listener; the cert CONTENT itself hot-reloads via the manager's mtime watcher, no restart needed
    # TLS everywhere (minio_tpu/secure/certs.py): enable=on wraps BOTH
    # listeners (S3 front + internode RPC) and both client stacks with
    # material from ``certs_dir`` (layout in docs/security.md:
    # public.crt/private.key, internode/, CAs/, sni/<host>/).  Cert
    # ROTATION is live — the manager re-stats the files and re-keys
    # the next connection; only flipping enable needs a restart.
    "enable": "off",
    "certs_dir": "",
})
register_subsys("policy_opa", {
    # external policy webhook (minio_tpu/secure/opa.py, the
    # cmd/config/policy/opa analog): when ``url`` is set,
    # IAMSys.is_allowed delegates every non-admin authorization
    # decision to POST {"input": {...}} at that URL and local policy
    # documents stop being evaluated.  FAIL-CLOSED: timeout/transport
    # error/non-2xx all deny; ``timeout`` bounds each attempt and
    # ``retry_attempts`` rides the shared jittered backoff.
    # Live-reloadable (S3Server.reload_policy_config on SetConfigKV).
    "url": "",
    "auth_token": "",
    "timeout": "2s",
    "retry_attempts": "2",
})
register_subsys("heal", {
    "bitrotscan": "off",
    "max_sleep": "1s",
    "max_io": "10",
})
register_subsys("scanner", {
    "delay": "10",
    "max_wait": "15s",
})
register_subsys("rebalance", {
    # pool drain/rebalance plane (background/rebalance.py): ``enable``
    # gates the background loop (admin pool-decommission still drains —
    # the route kicks the loop explicitly); ``max_workers`` bounds
    # concurrent key moves; ``bandwidth`` caps drain bytes/sec through
    # the replication token bucket (0 = unthrottled).  The healer's
    # heal.max_sleep pacing applies to moves too.  Live-reloadable
    # (S3Server.reload_background_config on admin SetConfigKV).
    "enable": "off",
    "max_workers": "1",
    "bandwidth": "0",
})
register_subsys("compression", {  # mt-lint: ok(kvconfig-drift) read per request (handlers_object.py) — applies to the next PUT/GET, no reload hook needed
    "enable": "off",
    "extensions": ".txt,.log,.csv,.json,.tar,.xml,.bin",
    "mime_types": "text/*,application/json,application/xml",
})
# log/audit webhook egress (cmd/logger/target/http QueueSize/QueueDir):
# queue_size bounds the in-memory sender queue, queue_dir enables the
# disk store behind it (store-and-forward, obs/egress.py) — both
# live-reloadable via admin SetConfigKV (reload_egress_config)
register_subsys("logger_webhook", {"enable": "off", "endpoint": "",
                                   "auth_token": "",
                                   "queue_size": "10000",
                                   "queue_dir": ""})
register_subsys("audit_webhook", {"enable": "off", "endpoint": "",
                                  "auth_token": "",
                                  "queue_size": "10000",
                                  "queue_dir": ""})
# watchdog alert delivery (obs/watchdog.py): firing/resolved alert
# events ride the same store-and-forward egress engine as the
# log/audit webhooks — bounded queue, optional disk store, replay
register_subsys("alert_webhook", {"enable": "off", "endpoint": "",
                                  "auth_token": "",
                                  "queue_size": "10000",
                                  "queue_dir": ""})
register_subsys("notify_webhook", {"enable": "off", "endpoint": "",
                                   "auth_token": "", "queue_dir": "",
                                   "queue_limit": "10000"})
register_subsys("federation", {  # mt-lint: ok(kvconfig-drift) construction-time (utils/fed_dns.py from_config at boot) — changing it requires a restart by design
    "enable": "off",
    "domain": "",                   # bucket.<domain> DNS zone
    "dns_file": "",                 # FileDNSStore path (etcd stand-in)
    "advertise": "",                # routable host:port in DNS records
})
register_subsys("etcd", {  # mt-lint: ok(kvconfig-drift) construction-time (utils/etcd.py client boot) — the coordination backend cannot be swapped live
    # cmd/config/etcd/etcd.go keys: the coordination backend for
    # config/IAM storage and CoreDNS federation records
    "endpoints": "",                # comma-separated http://host:port
    "path_prefix": "",              # namespace all keys (multi-tenant)
})
register_subsys("identity_ldap", {  # mt-lint: ok(kvconfig-drift) read per STS/login call (iam/ldap.py) — each auth round reads the live values
    # cmd/config/identity/ldap/config.go keys, 1:1
    "server_addr": "",
    "sts_expiry": "1h",
    "lookup_bind_dn": "",
    "lookup_bind_password": "",
    "user_dn_search_base_dn": "",
    "user_dn_search_filter": "",        # %s -> username
    "group_search_filter": "",          # %s -> username, %d -> user DN
    "group_search_base_dn": "",
})
register_subsys("identity_openid", {  # mt-lint: ok(kvconfig-drift) read per STS validation (iam/openid.py from_config) — each token check reads the live values
    "enable": "off",
    "issuer": "",                   # expected iss claim
    "client_id": "",                # expected aud/azp
    "claim_name": "policy",         # claim carrying IAM policy names
    "jwks_file": "",                # path to a JWKS document (RS256)
    "jwks": "",                     # inline JWKS JSON (overrides file)
    "hs256_secret": "",             # shared-secret mode
})
# broker notification subsystems (cmd/config/notify): keys mirror the
# reference's per-target config structs
register_subsys("notify_amqp", {"enable": "off", "url": "",
                                "exchange": "", "routing_key": "",
                                "queue_dir": "",
                                "queue_limit": "10000"})
register_subsys("notify_kafka", {"enable": "off", "brokers": "",
                                 "topic": "", "queue_dir": "",
                                 "queue_limit": "10000"})
register_subsys("notify_mqtt", {"enable": "off", "broker": "",
                                "topic": "", "qos": "0", "queue_dir": "",
                                "queue_limit": "10000"})
register_subsys("notify_nats", {"enable": "off", "address": "",
                                "subject": "", "username": "",
                                "password": "", "queue_dir": "",
                                "queue_limit": "10000"})
register_subsys("notify_nsq", {"enable": "off", "nsqd_address": "",
                               "topic": "", "queue_dir": "",
                               "queue_limit": "10000"})
register_subsys("notify_redis", {"enable": "off", "address": "",
                                 "key": "", "format": "namespace",
                                 "password": "", "queue_dir": "",
                                 "queue_limit": "10000"})
register_subsys("notify_mysql", {"enable": "off", "dsn_string": "",
                                 "table": "", "format": "namespace",
                                 "queue_dir": "",
                                 "queue_limit": "10000"})
register_subsys("notify_postgresql", {"enable": "off",
                                      "connection_string": "",
                                      "table": "", "format": "namespace",
                                      "queue_dir": "",
                                      "queue_limit": "10000"})
register_subsys("notify_elasticsearch", {"enable": "off", "url": "",
                                         "index": "",
                                         "format": "namespace",
                                         "queue_dir": "",
                                         "queue_limit": "10000"})


class Config:
    """Layered lookup: env > dynamic set > defaults.

    ``secret`` (the admin secret key) arms encrypted persistence
    (cmd/config-encrypted.go role): the dynamic layer lands on disk as
    a DARE blob under a credentials-derived key instead of plaintext
    JSON.  A plaintext blob found at load is migrated (re-persisted
    sealed), and one sealed under retired credentials
    (``MT_ADMIN_SECRET_OLD``) is re-sealed under the current secret —
    rotation re-encrypts in place.
    """

    def __init__(self, layer=None, secret: str | None = None):
        self._layer = layer
        self._secret = secret or ""
        self._dynamic: dict[str, dict[str, str]] = {}
        self._mu = mtlock("config.dynamic")
        self._persist_mu = mtlock("config.persist")
        if layer is not None:
            self._load()

    def _env_key(self, subsys: str, key: str) -> str:
        return f"{ENV_PREFIX}_{subsys.upper()}_{key.upper()}"

    def get(self, subsys: str, key: str) -> str:
        env = os.environ.get(self._env_key(subsys, key))
        if env is not None:
            return env
        with self._mu:
            dyn = self._dynamic.get(subsys, {}).get(key)
        if dyn is not None:
            return dyn
        spec = _REGISTRY.get(subsys)
        if spec is None or key not in spec.defaults:
            raise KeyError(f"{subsys}.{key}")
        return spec.defaults[key]

    def set(self, subsys: str, key: str, value: str) -> None:
        spec = _REGISTRY.get(subsys)
        if spec is None:
            raise KeyError(subsys)
        if key not in spec.defaults:
            raise KeyError(f"{subsys}.{key}")
        with self._mu:
            self._dynamic.setdefault(subsys, {})[key] = value
        self._persist()

    def get_subsys(self, subsys: str) -> dict[str, str]:
        spec = _REGISTRY.get(subsys)
        if spec is None:
            raise KeyError(subsys)
        return {k: self.get(subsys, k) for k in spec.defaults}

    def subsystems(self) -> list[str]:
        return sorted(_REGISTRY)

    def help(self, subsys: str) -> list[HelpKV]:
        return _REGISTRY[subsys].help

    # -- persistence (cmd/config-current.go analog) ------------------------

    def _persist(self) -> None:
        if self._layer is None:
            return
        from ..secure import configcrypt
        from ..storage.xl_storage import SYS_DIR
        with self._persist_mu:  # snapshot+write atomic wrt other persists
            with self._mu:
                blob = json.dumps(self._dynamic).encode()
            if self._secret:
                blob = configcrypt.encrypt_data(self._secret, blob)
            self._layer._fanout(
                lambda d: d.write_all(SYS_DIR, "config/config.json", blob))

    def _load(self) -> None:
        from ..secure import configcrypt
        from ..storage.xl_storage import SYS_DIR
        res, _ = self._layer._fanout(
            lambda d: d.read_all(SYS_DIR, "config/config.json"))
        olds = configcrypt.old_secrets_from_env()
        for r in res:
            if r is None:
                continue
            try:
                blob, reseal = configcrypt.maybe_decrypt(
                    self._secret, r, olds)
            except configcrypt.DecryptError:
                continue        # replica sealed under unknown creds
            try:
                with self._mu:
                    self._dynamic = json.loads(blob)
            except json.JSONDecodeError:
                continue
            if reseal and self._secret:
                # plaintext migration / credentials rotation: what we
                # just read goes back sealed under the CURRENT secret
                self._persist()
            return


def parse_storage_class(value: str, drive_count: int) -> int | None:
    """'EC:4' -> parity 4 (cmd/config/storageclass/storage-class.go)."""
    if not value:
        return None
    if not value.startswith("EC:"):
        raise ValueError(f"invalid storage class {value!r}")
    parity = int(value[3:])
    # parity 0 (no redundancy) is not a supported erasure geometry here:
    # the write path stripes data assuming at least one parity shard
    if parity < 1 or parity > drive_count // 2:
        raise ValueError(f"parity {parity} out of range")
    return parity
