"""Node memory governor — admission control for unbounded-ish work.

The data plane bounds its own memory per request (PUT pipeline
O(depth x batch), GET O(batch), RPC streaming O(chunk), Select
O(block), listing O(block)), but a node serving MANY such requests at
once can still exceed what the host has.  The governor is the
cluster-facing admission layer on top: every memory-hungry request
path (Select scanners, listing walks, multipart assembly) charges its
bounded working-set estimate here before allocating, and a charge that
would push the node past the configured watermark is refused with
:class:`MemoryPressure` — the S3 frontend turns that into a 503 +
``Retry-After`` through the PR-1 load-shed path, so pressure degrades
into polite shedding instead of an OOM kill (the role maxClients +
deadline play in cmd/handler-api.go, extended to bytes).

Semantics:

* ``limit_bytes == 0`` disables admission entirely (charges are still
  accounted, so ``mt_mem_{inuse,peak}_bytes`` stay observable);
* charges are cheap integer bookkeeping — the governor never measures
  the heap, it trusts the bounded estimates the charging sites derive
  from their own block/depth knobs;
* every charge is a context manager / explicit release, so a dying
  request (client disconnect, handler exception) always returns its
  bytes — asserted by tests/test_leaks.py.

Knobs live in the ``api`` kvconfig subsystem (``mem_limit``,
``mem_retry_after``) and are pushed live by
``S3Server.reload_api_config`` on admin SetConfigKV.
"""

from __future__ import annotations


from ..admin.metrics import GLOBAL as _metrics
from .locktrace import mtrlock


# kinds that legitimately stay charged between requests: bounded
# resident tiers (the hot-object cache), bounded by their own knobs
# and released on server stop — everything else is request-scoped and
# must settle to zero at idle
RESIDENT_KINDS = frozenset({"cache"})


class MemoryPressure(Exception):
    """Raised when a charge would exceed the configured watermark; the
    S3 layer maps it to 503 SlowDown + Retry-After."""

    def __init__(self, kind: str, want: int, inuse: int, limit: int,
                 retry_after_s: float = 1.0):
        super().__init__(
            f"memory governor: {kind} charge of {want} B refused "
            f"({inuse} B in use, limit {limit} B)")
        self.kind = kind
        self.want = want
        self.inuse = inuse
        self.limit = limit
        self.retry_after_s = retry_after_s


def parse_size(s: str, default: int = 0) -> int:
    """'268435456' / '256MiB' / '1GiB' -> bytes (config size keys)."""
    s = (s or "").strip()
    mult = 1
    for suffix, m in (("KiB", 1 << 10), ("MiB", 1 << 20),
                      ("GiB", 1 << 30), ("KB", 10 ** 3), ("MB", 10 ** 6),
                      ("GB", 10 ** 9), ("K", 1 << 10), ("M", 1 << 20),
                      ("G", 1 << 30), ("B", 1)):
        if s.endswith(suffix):
            s, mult = s[:-len(suffix)], m
            break
    try:
        return int(float(s) * mult)
    except ValueError:
        return default


class Charge:
    """One request's outstanding reservation; release is idempotent."""

    __slots__ = ("_gov", "kind", "nbytes", "_released")

    def __init__(self, gov: "MemoryGovernor", kind: str, nbytes: int):
        self._gov = gov
        self.kind = kind
        self.nbytes = nbytes
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._gov._release(self.kind, self.nbytes)

    def __enter__(self) -> "Charge":
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __del__(self):  # abandoned mid-request: never leak the bytes
        self.release()


class MemoryGovernor:
    """Watermark-based byte accounting shared by every charging site."""

    def __init__(self, limit_bytes: int = 0, retry_after_s: float = 1.0):
        # REENTRANT: Charge.__del__ releases via this lock, and cyclic
        # GC can fire inside a locked section on the same thread (an
        # allocation under charge()/stats() collecting a leaked
        # Charge) — a plain Lock would self-deadlock the request
        # thread; RLock makes the nested release safe
        self._mu = mtrlock("memgov.governor")
        self.limit_bytes = limit_bytes
        self.retry_after_s = retry_after_s
        self._inuse: dict[str, int] = {}
        self._peak = 0
        self._shed: dict[str, int] = {}

    def configure(self, limit_bytes: int,
                  retry_after_s: float | None = None) -> None:
        with self._mu:
            self.limit_bytes = max(0, int(limit_bytes))
            if retry_after_s is not None:
                self.retry_after_s = max(0.0, float(retry_after_s))

    # -- accounting --------------------------------------------------------

    def _admit(self, nbytes: int, kind: str, shed: bool
               ) -> "Charge | None":
        """The ONE admission core: watermark check + accounting.
        ``shed=True`` refusals tick ``mt_mem_shed_total`` and raise
        MemoryPressure; ``shed=False`` refusals quietly return None."""
        nbytes = max(0, int(nbytes))
        with self._mu:
            inuse = sum(self._inuse.values())
            if self.limit_bytes and inuse + nbytes > self.limit_bytes:
                if not shed:
                    return None
                self._shed[kind] = self._shed.get(kind, 0) + 1
                retry = self.retry_after_s
                _metrics.inc("mt_mem_shed_total", {"kind": kind})
                raise MemoryPressure(kind, nbytes, inuse,
                                     self.limit_bytes, retry)
            self._inuse[kind] = self._inuse.get(kind, 0) + nbytes
            self._peak = max(self._peak, inuse + nbytes)
        return Charge(self, kind, nbytes)

    def charge(self, nbytes: int, kind: str = "other") -> Charge:
        """Reserve ``nbytes`` for one request; raises MemoryPressure
        when the node is past its watermark (shed, don't allocate).
        The admission's wall time lands in the ``memgov`` X-ray stage
        (obs/stages.py) — cheap bookkeeping, but a contended governor
        lock under pressure is exactly what the X-ray must surface."""
        import time as _time

        from ..obs import stages as _stages
        t0 = _time.monotonic_ns()
        try:
            return self._admit(nbytes, kind, shed=True)
        finally:
            _stages.add("memgov", _time.monotonic_ns() - t0)

    def try_charge(self, nbytes: int, kind: str = "other"
                   ) -> "Charge | None":
        """Non-shedding admission for OPTIONAL allocations (the hot
        cache filling a window): returns ``None`` instead of raising
        when the node is past its watermark, and never ticks
        ``mt_mem_shed_total`` — declining to cache is not a shed
        request, just a cache that stops growing under pressure."""
        return self._admit(nbytes, kind, shed=False)

    def _release(self, kind: str, nbytes: int) -> None:
        with self._mu:
            cur = self._inuse.get(kind, 0) - nbytes
            if cur > 0:
                self._inuse[kind] = cur
            else:
                self._inuse.pop(kind, None)

    # -- observability -----------------------------------------------------

    def inuse_bytes(self, kind: str | None = None) -> int:
        with self._mu:
            if kind is not None:
                return self._inuse.get(kind, 0)
            return sum(self._inuse.values())

    def transient_bytes(self) -> int:
        """Outstanding REQUEST-scoped charges: total inuse minus the
        resident kinds (the hot-object cache's deliberately-held
        tier).  This is the figure that must settle to zero at idle —
        a non-zero residue here is a leaked request; resident bytes
        are bounded by their own knobs and released on shutdown."""
        with self._mu:
            return sum(v for k, v in self._inuse.items()
                       if k not in RESIDENT_KINDS)

    def stats(self) -> dict:
        with self._mu:
            return {"limit_bytes": self.limit_bytes,
                    "inuse": dict(self._inuse),
                    "peak_bytes": self._peak,
                    "shed": dict(self._shed)}

    @property
    def touched(self) -> bool:
        """Whether the governor has anything worth scraping (the idle
        contract: an unconfigured, uncharged governor emits nothing)."""
        with self._mu:
            return bool(self.limit_bytes or self._peak or self._shed)

    def load(self, config) -> None:
        """Pull the ``api`` kvconfig knobs (mem_limit, mem_retry_after)
        — called from S3Server.reload_api_config so admin SetConfigKV
        retunes the watermark on a live server."""
        from .kvconfig import parse_duration
        limit = parse_size(config.get("api", "mem_limit"), 0)
        retry = parse_duration(config.get("api", "mem_retry_after")
                               or "1s", 1.0)
        self.configure(limit, retry)


# process-global governor: one node = one memory budget, shared by
# every server/layer in the process (exactly like the codec batcher
# and the RPC streaming plane)
GOVERNOR = MemoryGovernor()
