"""etcd v3 client over the grpc-gateway JSON API (cmd/etcd.go role).

The reference links the etcd3 gRPC client (go.mod) for config/IAM
storage and CoreDNS federation records.  This image has no gRPC stack,
but every etcd v3 server also exposes the SAME KV API through its
grpc-gateway: plain HTTP POSTs of JSON bodies with base64 keys/values
(/v3/kv/put, /v3/kv/range, /v3/kv/deleterange) — full fidelity for the
put/get/prefix/delete surface the framework needs.  Tested against an
in-process stub speaking the identical wire protocol
(tests/etcd_stub.py), the same pattern the OIDC and LDAP subsystems
use in this zero-egress environment.
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.request


class EtcdError(Exception):
    pass


def _b64(data: bytes | str) -> str:
    if isinstance(data, str):
        data = data.encode()
    return base64.b64encode(data).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def prefix_range_end(prefix: bytes) -> bytes:
    """etcd prefix query: range_end = prefix with last byte + 1."""
    p = bytearray(prefix)
    for i in reversed(range(len(p))):
        if p[i] < 0xFF:
            p[i] += 1
            return bytes(p[:i + 1])
    return b"\x00"                     # whole keyspace


class EtcdClient:
    """Minimal KV client: put / get / get_prefix / delete(_prefix)."""

    def __init__(self, endpoints: list[str] | str, timeout: float = 10.0):
        if isinstance(endpoints, str):
            endpoints = [e.strip() for e in endpoints.split(",")
                         if e.strip()]
        if not endpoints:
            raise EtcdError("no etcd endpoints configured")
        self._eps = [e.rstrip("/") if e.startswith("http")
                     else f"http://{e.rstrip('/')}" for e in endpoints]
        self._timeout = timeout

    def _call(self, path: str, body: dict) -> dict:
        payload = json.dumps(body).encode()
        last: Exception | None = None
        for ep in self._eps:           # failover across endpoints
            try:
                req = urllib.request.Request(
                    ep + path, data=payload,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(
                        req, timeout=self._timeout) as resp:
                    return json.loads(resp.read() or b"{}")
            except (urllib.error.URLError, OSError,
                    json.JSONDecodeError) as e:
                last = e
                continue
        raise EtcdError(f"all etcd endpoints failed: {last}")

    def put(self, key: str | bytes, value: bytes | str) -> None:
        self._call("/v3/kv/put", {"key": _b64(key), "value": _b64(value)})

    def put_if_absent(self, key: str | bytes,
                      value: bytes | str) -> bool:
        """Atomic create (etcd txn with a create-revision guard): True
        when this call created the key, False when it already existed —
        the primitive federated MakeBucket races on (the reference uses
        the same etcd transaction)."""
        out = self._call("/v3/kv/txn", {
            "compare": [{"key": _b64(key), "target": "CREATE",
                         "result": "EQUAL", "create_revision": "0"}],
            "success": [{"request_put": {"key": _b64(key),
                                         "value": _b64(value)}}],
            "failure": [],
        })
        return bool(out.get("succeeded"))

    def get(self, key: str | bytes) -> bytes | None:
        out = self._call("/v3/kv/range", {"key": _b64(key)})
        kvs = out.get("kvs") or []
        return _unb64(kvs[0]["value"]) if kvs else None

    def get_prefix(self, prefix: str | bytes) -> list[tuple[bytes, bytes]]:
        p = prefix.encode() if isinstance(prefix, str) else prefix
        out = self._call("/v3/kv/range", {
            "key": _b64(p), "range_end": _b64(prefix_range_end(p))})
        return [(_unb64(kv["key"]), _unb64(kv["value"]))
                for kv in out.get("kvs") or []]

    def delete(self, key: str | bytes) -> int:
        out = self._call("/v3/kv/deleterange", {"key": _b64(key)})
        return int(out.get("deleted", 0))

    def delete_prefix(self, prefix: str | bytes) -> int:
        p = prefix.encode() if isinstance(prefix, str) else prefix
        out = self._call("/v3/kv/deleterange", {
            "key": _b64(p), "range_end": _b64(prefix_range_end(p))})
        return int(out.get("deleted", 0))

    def status(self) -> bool:
        try:
            self._call("/v3/kv/range", {"key": _b64(b"\x00")})
            return True
        except EtcdError:
            return False


def from_config(cfg) -> EtcdClient | None:
    """Build a client from the `etcd` config subsystem (None when the
    subsystem is unconfigured — callers fall back to drive storage)."""
    try:
        eps = cfg.get("etcd", "endpoints")
    except KeyError:
        return None
    if not eps:
        return None
    return EtcdClient(eps)
