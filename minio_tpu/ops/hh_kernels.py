"""Device-side HighwayHash-256 — bitrot verification fused on TPU.

The reference verifies every shard block with keyed HighwayHash256
(cmd/bitrot.go:30-57, AVX2 assembly in minio/highwayhash).  Here the
same hash runs ON the TPU so a batch of shard blocks can be encoded and
integrity-hashed in one device pipeline with no host round trip
(BASELINE.json config 5: "bitrot HighwayHash fused on-device").

TPU-first formulation: TPUs have no 64-bit integer units, so every u64
of HighwayHash state is a (hi, lo) uint32 pair and the 32x32->64
products are built from 16-bit partial products — the same limb trick
the reference's NEON port uses for lanes without 64-bit multiplies.
The packet loop is a lax.scan (sequential by construction: each packet
permutes the whole state); throughput comes from batching B independent
blocks per scan step, each carrying 4 hash lanes on the VPU.

Bit-identical to minio_tpu.hashing.highwayhash (and therefore to the
reference) — conformance-tested against the native C implementation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..hashing.highwayhash import MAGIC_KEY

_U32 = jnp.uint32
_MASK16 = np.uint32(0xFFFF)

_INIT_MUL0 = np.array(
    [0xDBE6D5D5FE4CCE2F, 0xA4093822299F31D0,
     0x13198A2E03707344, 0x243F6A8885A308D3], dtype=np.uint64)
_INIT_MUL1 = np.array(
    [0x3BD39E10CB0EF593, 0xC0ACF169B5F18A8C,
     0xBE5466CF34E90C6C, 0x452821E638D01377], dtype=np.uint64)


def _split(x64: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    return ((x64 >> np.uint64(32)).astype(np.uint32),
            (x64 & np.uint64(0xFFFFFFFF)).astype(np.uint32))


# -- u64-as-pair primitives (hi, lo are uint32 arrays) ----------------------

def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(_U32)
    return ah + bh + carry, lo


def _mul32(a, b):
    """Full 32x32 -> 64 product of uint32 arrays as (hi, lo)."""
    a0 = a & _MASK16
    a1 = a >> 16
    b0 = b & _MASK16
    b1 = b >> 16
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> 16) + (p01 & _MASK16) + (p10 & _MASK16)
    lo = (p00 & _MASK16) | (mid << 16)
    hi = p11 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)
    return hi, lo


def _shl64(h, l, s: int):
    if s == 0:
        return h, l
    if s >= 32:
        return (l << (s - 32)) if s > 32 else l, jnp.zeros_like(l)
    return (h << s) | (l >> (32 - s)), l << s


def _shr64(h, l, s: int):
    if s == 0:
        return h, l
    if s >= 32:
        return jnp.zeros_like(h), (h >> (s - 32)) if s > 32 else h
    return h >> s, (l >> s) | (h << (32 - s))


def _and64(h, l, c: int):
    ch = np.uint32(c >> 32)
    cl = np.uint32(c & 0xFFFFFFFF)
    return h & ch, l & cl


def _or3(*pairs):
    h = pairs[0][0]
    l = pairs[0][1]
    for ph, pl in pairs[1:]:
        h = h | ph
        l = l | pl
    return h, l


def _zipper(v1h, v1l, v0h, v0l):
    """ZipperMerge (highwayhash update permutation) on u64 pairs;
    returns (add1, add0) pairs.  Direct transcription of the reference
    mask/shift formulation (hashing/highwayhash.py _zipper)."""
    add0 = _or3(
        _shr64(*_or3(_and64(v0h, v0l, 0xFF000000),
                     _and64(v1h, v1l, 0xFF00000000)), 24),
        _shr64(*_or3(_and64(v0h, v0l, 0xFF0000000000),
                     _and64(v1h, v1l, 0xFF000000000000)), 16),
        _and64(v0h, v0l, 0xFF0000),
        _shl64(*_and64(v0h, v0l, 0xFF00), 32),
        _shr64(*_and64(v1h, v1l, 0xFF00000000000000), 8),
        _shl64(v0h, v0l, 56),
    )
    add1 = _or3(
        _shr64(*_or3(_and64(v1h, v1l, 0xFF000000),
                     _and64(v0h, v0l, 0xFF00000000)), 24),
        _and64(v1h, v1l, 0xFF0000),
        _shr64(*_and64(v1h, v1l, 0xFF0000000000), 16),
        _shl64(*_and64(v1h, v1l, 0xFF00), 24),
        _shr64(*_and64(v0h, v0l, 0xFF000000000000), 8),
        _shl64(*_and64(v1h, v1l, 0xFF), 48),
        _and64(v0h, v0l, 0xFF00000000000000),
    )
    return add1, add0


def _update(state, lanes_h, lanes_l):
    """One packet update; state arrays have shape (..., 4)."""
    v0h, v0l, v1h, v1l, m0h, m0l, m1h, m1l = state
    v1h, v1l = _add64(v1h, v1l, *_add64(m0h, m0l, lanes_h, lanes_l))
    ph, pl = _mul32(v1l, v0h)
    m0h, m0l = m0h ^ ph, m0l ^ pl
    v0h, v0l = _add64(v0h, v0l, m1h, m1l)
    ph, pl = _mul32(v0l, v1h)
    m1h, m1l = m1h ^ ph, m1l ^ pl

    def zip_into(vh, vl, sh, sl):
        """v0 += zipper(v1) on lane pairs (1,0) and (3,2)."""
        (a1h, a1l), (a0h, a0l) = _zipper(
            sh[..., 1::2], sl[..., 1::2], sh[..., 0::2], sl[..., 0::2])
        oh, ol = _add64(
            vh, vl,
            jnp.stack([a0h, a1h], axis=-1).reshape(vh.shape),
            jnp.stack([a0l, a1l], axis=-1).reshape(vl.shape))
        return oh, ol

    v0h, v0l = zip_into(v0h, v0l, v1h, v1l)
    v1h, v1l = zip_into(v1h, v1l, v0h, v0l)
    return (v0h, v0l, v1h, v1l, m0h, m0l, m1h, m1l)


def _rot32(h, l):
    """(x >> 32) | (x << 32): swap halves."""
    return l, h


def _permute_update(state):
    v0h, v0l = state[0], state[1]
    # lanes (2,3,0,1) with 32-bit halves swapped
    perm = (2, 3, 0, 1)
    lh = v0l[..., perm]          # swapped: hi <- lo
    ll = v0h[..., perm]
    return _update(state, lh, ll)


def _init_state_np(key: bytes) -> tuple[np.ndarray, ...]:
    """Initial (hi, lo) state limbs, computed host-side: JAX has no
    uint64 without x64 mode, so 64-bit init math stays in numpy."""
    k = np.frombuffer(key, dtype="<u8")
    krot = (k >> np.uint64(32)) | (k << np.uint64(32))
    m0h, m0l = _split(_INIT_MUL0)
    m1h, m1l = _split(_INIT_MUL1)
    v0h, v0l = _split(_INIT_MUL0 ^ k)
    v1h, v1l = _split(_INIT_MUL1 ^ krot)
    return v0h, v0l, v1h, v1l, m0h, m0l, m1h, m1l


def _rotl32(x, s: int):
    return (x << s) | (x >> (32 - s))


def _remainder_update(state, tail, rem: int):
    """Final partial packet (update_remainder, hashing/highwayhash.py):
    `tail` is (B, rem) uint8, rem in 1..31 — static, so the packet
    construction is all fixed indexing."""
    v0h, v0l, v1h, v1l, m0h, m0l, m1h, m1l = state
    B = tail.shape[0]
    # v0 += (size << 32) + size
    v0h, v0l = _add64(v0h, v0l, jnp.full_like(v0h, np.uint32(rem)),
                      jnp.full_like(v0l, np.uint32(rem)))
    # rotate each 32-bit half of v1 left by size
    v1h = _rotl32(v1h, rem)
    v1l = _rotl32(v1l, rem)
    size_mod4 = rem & 3
    rem_off = rem & ~3
    packet = jnp.zeros((B, 32), jnp.uint8)
    if rem_off:
        packet = packet.at[:, :rem_off].set(tail[:, :rem_off])
    if rem & 16:
        packet = packet.at[:, 28:32].set(
            tail[:, rem_off + size_mod4 - 4:rem_off + size_mod4])
    elif size_mod4:
        packet = packet.at[:, 16].set(tail[:, rem_off])
        packet = packet.at[:, 17].set(tail[:, rem_off + (size_mod4 >> 1)])
        packet = packet.at[:, 18].set(tail[:, rem_off + size_mod4 - 1])
    words = jax.lax.bitcast_convert_type(
        packet.reshape(B, 8, 4), jnp.uint32).reshape(B, 8)
    lh = words[:, 1::2]
    ll = words[:, 0::2]
    return _update((v0h, v0l, v1h, v1l, m0h, m0l, m1h, m1l), lh, ll)


@functools.partial(jax.jit, static_argnames=("rem",))
def _hh256_scan(packets_h, packets_l, init, tail=None, rem=0):
    """packets_[hl]: (P, B, 4) uint32 — P sequential packets over B
    independent blocks; init: 8 x (4,) uint32 state limbs; tail: (B,
    rem) uint8 final partial packet shared-length across the batch.
    Returns (B, 8) uint32 (the 256-bit digests as LE words)."""
    B = packets_h.shape[1]
    state = tuple(jnp.broadcast_to(jnp.asarray(a, _U32), (B, 4))
                  for a in init)

    def step(st, xs):
        lh, ll = xs
        return _update(st, lh, ll), None

    state, _ = jax.lax.scan(step, state, (packets_h, packets_l))
    if rem:
        state = _remainder_update(state, tail, rem)
    for _ in range(10):
        state = _permute_update(state)
    v0h, v0l, v1h, v1l, m0h, m0l, m1h, m1l = state

    def modred(a3h, a3l, a2h, a2l, a1h, a1l, a0h, a0l):
        a3h = a3h & np.uint32(0x3FFFFFFF)
        m1h_, m1l_ = a1h, a1l
        for s in (1, 2):
            # ((a3 << s) | (a2 >> (64 - s))): the a2 spill feeds only
            # the low bits of the low word
            th, tl = _shl64(a3h, a3l, s)
            tl = tl | (a2h >> (32 - s))
            m1h_, m1l_ = m1h_ ^ th, m1l_ ^ tl
        m0h_, m0l_ = a0h, a0l
        for s in (1, 2):
            th, tl = _shl64(a2h, a2l, s)
            m0h_, m0l_ = m0h_ ^ th, m0l_ ^ tl
        return m0h_, m0l_, m1h_, m1l_

    s10h, s10l = _add64(v0h, v0l, m0h, m0l)       # v0 + mul0 per lane
    s32h, s32l = _add64(v1h, v1l, m1h, m1l)       # v1 + mul1 per lane
    h0h, h0l, h1h, h1l = modred(
        s32h[..., 1], s32l[..., 1], s32h[..., 0], s32l[..., 0],
        s10h[..., 1], s10l[..., 1], s10h[..., 0], s10l[..., 0])
    h2h, h2l, h3h, h3l = modred(
        s32h[..., 3], s32l[..., 3], s32h[..., 2], s32l[..., 2],
        s10h[..., 3], s10l[..., 3], s10h[..., 2], s10l[..., 2])
    # LE u64 words -> (B, 8) uint32 little-endian word order
    return jnp.stack([h0l, h0h, h1l, h1h, h2l, h2h, h3l, h3h], axis=-1)


def hh256_batch(blocks, key: bytes = MAGIC_KEY):
    """HighwayHash-256 of B equal-sized blocks on device.

    blocks: (B, n) uint8 array (device or host), any uniform n — the
    final partial packet follows the reference's remainder rules, so
    real (non-32-aligned) shard sizes hash bit-identically.  Returns
    (B, 32) uint8 digests.
    """
    blocks = jnp.asarray(blocks, jnp.uint8)
    B, n = blocks.shape
    P, rem = n // 32, n % 32
    # (B, P, 32) bytes -> u32 lanes -> (P, B, 4) hi/lo
    words = jax.lax.bitcast_convert_type(
        blocks[:, :P * 32].reshape(B, P, 8, 4),
        jnp.uint32)                                # LE per 4 bytes
    words = words.reshape(B, P, 8)
    lo = words[..., 0::2].transpose(1, 0, 2)      # (P, B, 4)
    hi = words[..., 1::2].transpose(1, 0, 2)
    tail = blocks[:, P * 32:] if rem else None
    out = _hh256_scan(hi.astype(_U32), lo.astype(_U32),
                      _init_state_np(key), tail, rem)
    return jax.lax.bitcast_convert_type(
        out, jnp.uint8).reshape(B, 32)


def modred_reference(a3, a2, a1, a0):  # pragma: no cover - doc helper
    """The 256-bit modular reduction being mirrored (hashing/
    highwayhash.py finalize256) — kept for cross-reading."""
    M64 = (1 << 64) - 1
    a3 &= 0x3FFFFFFFFFFFFFFF
    m1 = a1 ^ (((a3 << 1) | (a2 >> 63)) & M64) ^ \
        (((a3 << 2) | (a2 >> 62)) & M64)
    m0 = a0 ^ ((a2 << 1) & M64) ^ ((a2 << 2) & M64)
    return m0, m1
