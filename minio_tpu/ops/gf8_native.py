"""Native (C++/AVX2) GF(2^8) matmul binding — host codec fast path.

The reference's erasure hot loop runs klauspost/reedsolomon's VPSHUFB
split-nibble assembly (go.mod:41).  `native/gf8.cc` is that kernel for
this framework's host path; the TPU device kernels (rs_kernels.py)
remain the headline compute plane.  The GF multiplication table is
handed to the library from gf8.py at init, so native and numpy results
are identical by construction (and asserted in tests/test_gf8_native.py).

Built on demand with g++ (same pattern as minio_tpu/compress);
``available()`` returns False and callers fall back to numpy when no
compiler is present or MT_NATIVE=0.
"""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

from ..utils import nativelib

_NATIVE_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "gf8.cc")
_NATIVE_SO = os.path.join(os.path.dirname(_NATIVE_SRC), "build",
                          "libmtgf8.so")

_lock = threading.Lock()
_lib = None
_lib_tried = False


def _load():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    with _lock:
        if _lib_tried:
            return _lib
        lib = nativelib.load(_NATIVE_SRC, _NATIVE_SO)
        if lib is not None:
            try:
                lib.mt_gf8_init.argtypes = [ctypes.c_char_p]
                lib.mt_gf8_matmul.argtypes = [
                    ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
                    ctypes.c_void_p, ctypes.c_size_t,
                    ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t]
                from . import gf8
                lib.mt_gf8_init(np.ascontiguousarray(gf8.GF_MUL).tobytes())
            except Exception:  # noqa: BLE001 — fall back to numpy
                lib = None
        _lib = lib
        # publish AFTER init completes: a concurrent caller must never
        # observe tried=True with a half-initialized library
        _lib_tried = True
        return _lib


def available() -> bool:
    return _load() is not None


def matmul_into(A: np.ndarray, B_view: np.ndarray,
                out_view: np.ndarray) -> None:
    """GF (r,k) x B_view (k,len) -> out_view (r,len), writing IN PLACE.

    Rows of both views must be contiguous (stride 1 on the last axis)
    but the row stride is arbitrary — the zero-copy PUT pipeline points
    this straight at the payload slots of bitrot-framed shard buffers,
    so parity lands in its final on-disk position with no intermediate
    array.  GIL released for the duration (ctypes)."""
    lib = _load()
    assert lib is not None
    r, k = A.shape
    k2, n = B_view.shape
    assert k == k2 and out_view.shape == (r, n)
    assert B_view.strides[1] == 1 and out_view.strides[1] == 1
    lib.mt_gf8_matmul(
        np.ascontiguousarray(A, dtype=np.uint8).tobytes(), r, k,
        B_view.ctypes.data_as(ctypes.c_void_p), B_view.strides[0],
        out_view.ctypes.data_as(ctypes.c_void_p), out_view.strides[0],
        n)


def matmul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """GF (r,k) x (k,len) -> (r,len); ctypes releases the GIL for the
    duration of the C call, so concurrent PUTs scale across threads."""
    lib = _load()
    assert lib is not None
    A = np.ascontiguousarray(A, dtype=np.uint8)
    B = np.ascontiguousarray(B, dtype=np.uint8)
    r, k = A.shape
    k2, n = B.shape
    assert k == k2
    out = np.empty((r, n), dtype=np.uint8)
    lib.mt_gf8_matmul(A.tobytes(), r, k,
                      B.ctypes.data_as(ctypes.c_void_p), B.strides[0],
                      out.ctypes.data_as(ctypes.c_void_p), out.strides[0],
                      n)
    return out
