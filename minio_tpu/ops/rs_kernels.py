"""TPU Reed-Solomon kernels: GF(2^8) coding as MXU matmuls.

TPU-first reformulation of the reference hot path (cmd/erasure-coding.go
EncodeData/DecodeDataBlocks, backed there by AVX2 assembly in
klauspost/reedsolomon):

GF(2^8) multiplication by a constant is linear over GF(2), so every
coefficient expands to an 8x8 bit matrix (gf8.gf2_expand).  A stripe of k
shards x n bytes unpacks to (8k, n) bits, and encode/decode becomes

    out_bits = M2 @ data_bits   (mod 2),   M2 in {0,1}^(8r x 8k)

i.e. an int8 matmul on the MXU followed by ``& 1``.  XOR-accumulation is
recovered from integer accumulation by parity (sum mod 2 == XOR for bits).
The same kernel serves encode (M2 = expanded parity rows) and decode
(M2 = expanded rows of the inverted survivor submatrix), so one compiled
executable per shape handles every missing-shard pattern -- no dynamic
shapes under jit.

Batching: stripes are batched on a leading axis so large objects are one
device dispatch, keeping the MXU fed (SURVEY.md section 7).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from . import gf8

_LANES = 128    # TPU lane width; byte axis is padded to a lane multiple
_MAX_BATCH = 64  # stripes per dispatch; batch axis is bucketed to powers of 2


@jax.jit
def _gf2_apply(matrix_bits: jax.Array, data: jax.Array) -> jax.Array:
    """Apply an expanded GF(2) matrix to batched byte shards.

    matrix_bits: (R, 8k) int8 with R = 8*out_shards
    data:        (B, k, n) uint8
    returns      (B, R//8, n) uint8
    """
    B, k, n = data.shape
    R = matrix_bits.shape[0]
    shifts = jnp.arange(8, dtype=jnp.uint8)
    # unpack LSB-first: (B, k, 8, n) -> (B, 8k, n)
    bits = ((data[:, :, None, :] >> shifts[None, None, :, None]) & 1)
    bits = bits.reshape(B, 8 * k, n).astype(jnp.int8)
    # (R, 8k) @ (B, 8k, n) -> (R, B, n) on the MXU, int32 accumulation
    acc = jax.lax.dot_general(
        matrix_bits, bits,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    par = (acc & 1).astype(jnp.uint8)              # parity == XOR reduction
    par = par.reshape(R // 8, 8, B, n)
    weights = (jnp.uint8(1) << shifts)[None, :, None, None]
    packed = (par * weights).sum(axis=1, dtype=jnp.uint8)  # (R//8, B, n)
    return packed.transpose(1, 0, 2)


@functools.lru_cache(maxsize=256)
def _device_matrix(key: bytes, rows: int, cols: int) -> jax.Array:
    """Expanded coefficient matrix, cached on device by content.

    Bounded: decode matrices vary per survivor pattern (C(n,k) of them), so
    an unbounded cache would pin device buffers forever on a healing server.
    """
    M = np.frombuffer(key, dtype=np.uint8).reshape(rows, cols)
    return jnp.asarray(gf8.gf2_expand(M), dtype=jnp.int8)


def _put_matrix(M: np.ndarray) -> jax.Array:
    M = np.ascontiguousarray(M, dtype=np.uint8)
    return _device_matrix(M.tobytes(), M.shape[0], M.shape[1])


def _use_pallas() -> bool:
    """Fused pallas kernel on real TPU (bit planes never touch HBM);
    the XLA formulation elsewhere.  MT_RS_PALLAS=0 forces XLA on TPU,
    =1 forces the pallas kernel (interpreter off-TPU) for testing."""
    env = os.environ.get("MT_RS_PALLAS", "auto")
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def apply_matrix(M: np.ndarray, shards: np.ndarray | jax.Array) -> np.ndarray:
    """out[b] = M (GF) @ shards[b] for a batch of stripes.

    M: (r, k) uint8 GF coefficients;  shards: (B, k, n) uint8.
    Returns (B, r, n) uint8 (numpy, host).
    """
    squeeze = getattr(shards, "ndim", 3) == 2
    if squeeze:
        shards = shards[None]
    pallas = _use_pallas()
    if pallas:
        from . import rs_pallas
    else:
        mb = _put_matrix(M)
    on_device = isinstance(shards, jax.Array)
    if not on_device:
        shards = np.asarray(shards, dtype=np.uint8)
    B, k, n = shards.shape
    # Bucket both variable axes so the jit cache stays small and tiles stay
    # full: byte axis padded to a lane multiple, batch axis chunked to
    # _MAX_BATCH and padded to the next power of two.  Device-resident
    # input stays on device (no host round trip); all chunks are
    # dispatched before any result is pulled back, so XLA overlaps MXU
    # work with D2H transfer.  Both properties hold for the pallas and
    # XLA kernels alike.
    xp = jnp if on_device else np
    pad_n = (-n) % _LANES
    if pad_n:
        shards = xp.pad(shards, ((0, 0), (0, 0), (0, pad_n)))
    handles = []
    for off in range(0, B, _MAX_BATCH):
        chunk = shards[off: off + _MAX_BATCH]
        b = chunk.shape[0]
        bb = 1 << (b - 1).bit_length()  # next power of two
        if bb != b:
            chunk = xp.pad(chunk, ((0, bb - b), (0, 0), (0, 0)))
        if pallas:
            handles.append((rs_pallas.apply_matrix(M, chunk), b))
        else:
            handles.append((_gf2_apply(mb, jnp.asarray(chunk)), b))
    chunks = [np.asarray(out[:b]) for out, b in handles]
    res = chunks[0] if len(chunks) == 1 else np.concatenate(chunks)
    if pad_n:
        res = res[..., :n]
    return res[0] if squeeze else res


def encode_parity(data_shards: np.ndarray, parity: int,
                  matrix: np.ndarray | None = None) -> np.ndarray:
    """(B, k, n) or (k, n) data -> (B, m, n) / (m, n) parity on TPU."""
    squeeze = data_shards.ndim == 2
    if squeeze:
        data_shards = data_shards[None]
    k = data_shards.shape[1]
    if matrix is None:
        matrix = gf8.rs_matrix(k, k + parity)
    out = apply_matrix(np.asarray(matrix)[k:], data_shards)
    return out[0] if squeeze else out


def decode_rows(matrix: np.ndarray, data_blocks: int,
                present: list[int], wanted: list[int]) -> np.ndarray:
    """Host-side tiny GF solve: rows mapping k survivors -> wanted shards.

    present: indices (sorted) of the k shards used for reconstruction.
    wanted:  shard indices to produce (data or parity).
    Returns (len(wanted), k) GF coefficient rows to feed apply_matrix.
    """
    assert len(present) == data_blocks
    sub = np.asarray(matrix)[present]              # (k, k)
    dec = gf8.gf_mat_inv(sub)                      # survivors -> data
    rows = []
    for w in wanted:
        if w < data_blocks:
            rows.append(dec[w])
        else:
            # parity row composed with the decode: parity_w = M[w] @ data
            rows.append(gf8.gf_matmul(np.asarray(matrix)[w][None, :], dec)[0])
    return np.stack(rows).astype(np.uint8)


def reconstruct(shards: list[np.ndarray | None], data_blocks: int,
                parity_blocks: int, data_only: bool = False,
                matrix: np.ndarray | None = None,
                apply=None) -> list[np.ndarray]:
    """TPU-backed equivalent of gf8_ref.reconstruct (one stripe).

    ``apply`` swaps the matmul engine — rs_mesh passes its sharded
    distributed_apply so the same survivor/solve logic serves both the
    single-chip and the mesh backend."""
    if apply is None:
        apply = apply_matrix
    total = data_blocks + parity_blocks
    if len(shards) != total:
        raise ValueError("wrong shard count")
    present = [i for i, s in enumerate(shards)
               if s is not None and len(s) > 0]
    if len(present) < data_blocks:
        from .gf8_ref import ReconstructError
        raise ReconstructError(
            f"need {data_blocks} shards, have {len(present)}")
    if matrix is None:
        matrix = gf8.rs_matrix(data_blocks, total)
    limit = total if not data_only else data_blocks
    missing = [i for i in range(limit)
               if shards[i] is None or len(shards[i]) == 0]
    out = list(shards)
    if not missing:
        return out
    use = present[:data_blocks]
    rows = decode_rows(matrix, data_blocks, use, missing)
    stack = np.stack([np.asarray(shards[i], dtype=np.uint8) for i in use])
    rebuilt = apply(rows, stack[None])[0]
    for j, i in enumerate(missing):
        out[i] = np.asarray(rebuilt[j], dtype=np.uint8)
    return out


def reconstruct_batch(shards: np.ndarray, present: list[int],
                      wanted: list[int], data_blocks: int,
                      parity_blocks: int,
                      matrix: np.ndarray | None = None) -> np.ndarray:
    """Batched reconstruction: same missing pattern across B stripes.

    shards: (B, k, n) -- the k surviving shards (rows ordered by ``present``).
    Returns (B, len(wanted), n).
    """
    if matrix is None:
        matrix = gf8.rs_matrix(data_blocks, data_blocks + parity_blocks)
    rows = decode_rows(matrix, data_blocks, list(present), list(wanted))
    return apply_matrix(rows, shards)
