"""HighwayHash-256 as ONE fused Pallas TPU kernel.

The lax.scan formulation (hh_kernels.py) pays per-op dispatch latency
2732 times per shard batch — honest chained measurement puts it at
~2-7 GiB/s no matter the batch width, because each of the ~80 u32 ops
per packet runs as its own tiny VPU dispatch inside the while loop.

This kernel runs the ENTIRE packet loop inside a single Mosaic kernel:

* state lives in VMEM scratch as 32 (S, 128)-tile u32 limb planes
  (4 vars x 4 u64 lanes x hi/lo), carried across a packet-chunk grid
  dimension (the standard revisiting-accumulator pattern);
* the shard batch rides the VPU lane dimension: every op processes a
  full (S, 128) tile of independent shards, so the sequential packet
  chain costs VLIW-issue slots, not kernel dispatches;
* the lane dimension of the hash (4 u64 lanes) is fully unrolled in
  the kernel body — the zipper-merge permutation becomes explicit
  variable wiring, reusing hh_kernels' shape-generic u64-pair helpers;
* tail packets in the final chunk are masked with selects (the packet
  count is rarely a multiple of the chunk size);
* the remainder packet + finalization (10 permutes, modular
  reduction) run as plain jnp on the (B, 4) state — ~90 tiny ops once
  per batch, not per packet.

Bit-identical to minio_tpu.hashing.highwayhash (reference:
cmd/bitrot.go:30-57, minio/highwayhash AVX2 assembly) — conformance-
tested against the host C path in tests/test_hh_device.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..hashing.highwayhash import MAGIC_KEY
from . import hh_kernels as hk

_U32 = jnp.uint32

# packets per grid step.  The kernel holds the input block AND its
# in-VMEM byte-plane transpose simultaneously (plus double-buffered
# prefetch), so the chunk is sized to keep the working set well under
# the 16 MiB scoped-vmem limit: 64 packets -> 2 MiB block,
# 2+2+2 MiB resident (a 128-packet chunk measured 17 MiB > limit)
_PC_NAT = 64


def _update_lanes(state, lanes):
    """One packet update with the 4 u64 hash lanes fully unrolled.

    state: dict var -> list of 4 (hi, lo) pairs; lanes: list of 4
    (hi, lo) pairs.  Mirrors hh_kernels._update exactly (same helper
    arithmetic), with the lane-sliced zipper interleave written as
    explicit pair wiring."""
    v0, v1, m0, m1 = state["v0"], state["v1"], state["m0"], state["m1"]
    v0 = list(v0)
    v1 = list(v1)
    m0 = list(m0)
    m1 = list(m1)
    for i in range(4):
        v1[i] = hk._add64(*v1[i], *hk._add64(*m0[i], *lanes[i]))
    for i in range(4):
        ph, plo = hk._mul32(v1[i][1], v0[i][0])
        m0[i] = (m0[i][0] ^ ph, m0[i][1] ^ plo)
    for i in range(4):
        v0[i] = hk._add64(*v0[i], *m1[i])
    for i in range(4):
        ph, plo = hk._mul32(v0[i][1], v1[i][0])
        m1[i] = (m1[i][0] ^ ph, m1[i][1] ^ plo)
    # v0 += zipper(v1) on lane pairs (1,0) and (3,2)
    for base in (0, 2):
        add1, add0 = hk._zipper(*v1[base + 1], *v1[base])
        v0[base] = hk._add64(*v0[base], *add0)
        v0[base + 1] = hk._add64(*v0[base + 1], *add1)
    # v1 += zipper(v0)
    for base in (0, 2):
        add1, add0 = hk._zipper(*v0[base + 1], *v0[base])
        v1[base] = hk._add64(*v1[base], *add0)
        v1[base + 1] = hk._add64(*v1[base + 1], *add1)
    return {"v0": v0, "v1": v1, "m0": m0, "m1": m1}


# limb plane order in scratch/output: var-major, lane, then hi/lo
_VARS = ("v0", "v1", "m0", "m1")


def _flatten(state):
    out = []
    for v in _VARS:
        for lane in range(4):
            out.extend(state[v][lane])          # hi, lo
    return out


def _unflatten(flat):
    state = {}
    i = 0
    for v in _VARS:
        lanes = []
        for _ in range(4):
            lanes.append((flat[i], flat[i + 1]))
            i += 2
        state[v] = lanes
    return state


def _kernel_nat(in_ref, out_ref, st, tbuf, *, S, n_packets, init_consts):
    """Grid step over NATURAL-layout shard bytes: in_ref is
    (S*128, _PC_NAT*32) uint8 — rows are shards, columns byte offsets.

    The byte-plane transpose happens HERE, in VMEM, as the kernel
    prologue (swapaxes into the ``tbuf`` scratch), instead of as a
    separate pallas transpose kernel: the standalone transpose costs a
    full extra HBM round trip of the entire operand (~2 ms per 340 MiB
    step measured on v5e), which was the single largest serial stage
    left in the fused encode+bitrot pipeline (BENCH_r03 detail).  The
    packet loop is the standard revisiting-accumulator pattern: state
    lives in the ``st`` scratch, carried across the packet-chunk grid
    dimension; the tail chunk is handled by the loop BOUND, not
    per-packet selects (masking the 32 carried limb planes measured
    8.5x the whole update)."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        for idx, c in enumerate(init_consts):
            st[idx] = jnp.full((S, 128), np.uint32(c), _U32)

    tbuf[:] = jnp.swapaxes(in_ref[:], 0, 1).reshape(_PC_NAT * 32, S, 128)

    carry0 = tuple(st[idx] for idx in range(32))

    def body(p, carry):
        x = tbuf[pl.ds(p * 32, 32)].astype(_U32)     # (32, S, 128)
        lanes = []
        for lane in range(4):
            b = 8 * lane
            lo = (x[b] | (x[b + 1] << 8) | (x[b + 2] << 16)
                  | (x[b + 3] << 24))
            hi = (x[b + 4] | (x[b + 5] << 8) | (x[b + 6] << 16)
                  | (x[b + 7] << 24))
            lanes.append((hi, lo))
        return tuple(_flatten(_update_lanes(_unflatten(list(carry)),
                                            lanes)))

    valid = jnp.minimum(_PC_NAT, n_packets - j * _PC_NAT)
    final = jax.lax.fori_loop(0, valid, body, carry0)
    for idx in range(32):
        st[idx] = final[idx]

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        for idx in range(32):
            out_ref[0, idx] = st[idx]


@functools.partial(jax.jit, static_argnames=("n_packets", "S"))
def _run_nat(x2d, n_packets, S):
    """x2d: (B_pad, P_pad*32) uint8 natural-layout shard bytes (row =
    one shard).  Returns (NB, 32, S, 128) u32 state planes.  2-D u8
    operands reach pallas in canonical layout, so no XLA layout copy
    sits between the producer kernel and this one."""
    bt, cols = x2d.shape
    nb = bt // (S * 128)
    npc = cols // (32 * _PC_NAT)
    init = _init_consts()
    kernel = functools.partial(_kernel_nat, S=S, n_packets=n_packets,
                               init_consts=init)
    return pl.pallas_call(
        kernel,
        grid=(nb, npc),
        in_specs=[pl.BlockSpec((S * 128, _PC_NAT * 32),
                               lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((1, 32, S, 128),
                               lambda i, j: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, 32, S, 128), _U32),
        scratch_shapes=[pltpu.VMEM((32, S, 128), _U32),
                        pltpu.VMEM((_PC_NAT * 32, S, 128), jnp.uint8)],
        interpret=jax.default_backend() != "tpu",
    )(x2d)


@functools.lru_cache(maxsize=1)
def _init_consts() -> tuple[int, ...]:
    """32 scalar u32 init limbs in plane order (key = MAGIC_KEY)."""
    v0h, v0l, v1h, v1l, m0h, m0l, m1h, m1l = hk._init_state_np(MAGIC_KEY)
    per_var = {"v0": (v0h, v0l), "v1": (v1h, v1l),
               "m0": (m0h, m0l), "m1": (m1h, m1l)}
    out = []
    for v in _VARS:
        hi, lo = per_var[v]
        for lane in range(4):
            out.append(int(hi[lane]))
            out.append(int(lo[lane]))
    return tuple(out)


def hh256_batch(blocks, key: bytes = MAGIC_KEY):
    """Drop-in for hh_kernels.hh256_batch, pallas packet loop.

    blocks: (B, n) uint8.  Returns (B, 32) uint8 digests, bit-identical
    to the reference HighwayHash256 with the bitrot magic key."""
    if key != MAGIC_KEY:
        raise ValueError("pallas path supports the bitrot magic key only")
    blocks = jnp.asarray(blocks, jnp.uint8)
    B, n = blocks.shape
    P, rem = n // 32, n % 32
    if P == 0 or B == 0:
        return hk.hh256_batch(blocks, key)

    # adapt the shard tile to the batch: a 16-shard tail call must not
    # pad (and hash) 1008 garbage rows.  Mosaic requires the 2nd-minor
    # block dim to be 8-divisible or equal to the whole array dim, so:
    # small batches use S=G (one tile block), larger ones S=8 + padding
    G = -(-B // 128)
    S = G if G < 8 else 8
    tb = S * 128
    b_pad = -B % tb
    p_pad = -P % _PC_NAT
    # pad in 2-D BYTE layout (safe: 2-D u8 operands reach pallas in
    # canonical layout), then ONE kernel: the byte-plane transpose is
    # the hash kernel's in-VMEM prologue (_kernel_nat), so the operand
    # crosses HBM exactly once.  Two designs this replaced, both
    # measured: a standalone pallas transpose kernel costs an extra
    # full HBM read+write of the operand (capped the fused pipeline at
    # 20.65 GiB/s, r3); an XLA-op-produced 3-D u8 operand reaches a
    # pallas call through a ~45 GB/s layout-conversion copy (r2).
    x = blocks[:, :P * 32]
    if b_pad or p_pad:
        x = jnp.pad(x, ((0, b_pad), (0, p_pad * 32)))
    bt = B + b_pad

    planes = _run_nat(x, P, S)                   # (NB, 32, S, 128)
    flat = [planes[:, idx].reshape(bt)[:B] for idx in range(32)]
    state = _unflatten(flat)
    # reassemble (B, 4) limb arrays for the existing finalize path
    st8 = []
    for v in _VARS:
        for part in (0, 1):                      # hi then lo
            st8.append(jnp.stack([state[v][lane][part]
                                  for lane in range(4)], axis=-1))
    state8 = tuple(st8)
    if rem:
        state8 = hk._remainder_update(state8, blocks[:, P * 32:], rem)
    return _finalize(state8)


@jax.jit
def _finalize(state8):
    state = state8
    for _ in range(10):
        state = hk._permute_update(state)
    v0h, v0l, v1h, v1l, m0h, m0l, m1h, m1l = state

    s10h, s10l = hk._add64(v0h, v0l, m0h, m0l)
    s32h, s32l = hk._add64(v1h, v1l, m1h, m1l)

    def modred(a3h, a3l, a2h, a2l, a1h, a1l, a0h, a0l):
        a3h = a3h & np.uint32(0x3FFFFFFF)
        m1h_, m1l_ = a1h, a1l
        for s in (1, 2):
            th, tl = hk._shl64(a3h, a3l, s)
            tl = tl | (a2h >> (32 - s))
            m1h_, m1l_ = m1h_ ^ th, m1l_ ^ tl
        m0h_, m0l_ = a0h, a0l
        for s in (1, 2):
            th, tl = hk._shl64(a2h, a2l, s)
            m0h_, m0l_ = m0h_ ^ th, m0l_ ^ tl
        return m0h_, m0l_, m1h_, m1l_

    h0h, h0l, h1h, h1l = modred(
        s32h[..., 1], s32l[..., 1], s32h[..., 0], s32l[..., 0],
        s10h[..., 1], s10l[..., 1], s10h[..., 0], s10l[..., 0])
    h2h, h2l, h3h, h3l = modred(
        s32h[..., 3], s32l[..., 3], s32h[..., 2], s32l[..., 2],
        s10h[..., 3], s10l[..., 3], s10h[..., 2], s10l[..., 2])
    out = jnp.stack([h0l, h0h, h1l, h1h, h2l, h2h, h3l, h3h], axis=-1)
    return jax.lax.bitcast_convert_type(out, jnp.uint8).reshape(-1, 32)
