"""Pure-numpy Reed-Solomon reference codec (conformance oracle).

Mirrors the behavior of klauspost/reedsolomon as used by MinIO's
cmd/erasure-coding.go: systematic Vandermonde matrix, Encode computes parity,
ReconstructData/Reconstruct rebuild missing shards from any k survivors.
The TPU kernels (rs_kernels.py) are validated bit-for-bit against this.
"""

from __future__ import annotations

import numpy as np

from . import gf8


class ReconstructError(ValueError):
    """Too few shards to reconstruct (reedsolomon.ErrTooFewShards)."""


def encode_parity(data_shards: np.ndarray, parity: int,
                  matrix: np.ndarray | None = None) -> np.ndarray:
    """(k, n) data -> (m, n) parity via the bottom m rows of the RS matrix."""
    k, _ = data_shards.shape
    if matrix is None:
        matrix = gf8.rs_matrix(k, k + parity)
    return gf8.gf_matmul(matrix[k:], data_shards)


def encode(data_shards: np.ndarray, parity: int) -> np.ndarray:
    """(k, n) -> (k+m, n) full shard set."""
    return np.concatenate(
        [data_shards, encode_parity(data_shards, parity)], axis=0)


def reconstruct(shards: list[np.ndarray | None], data_blocks: int,
                parity_blocks: int, data_only: bool = False,
                matrix: np.ndarray | None = None) -> list[np.ndarray]:
    """Rebuild missing (None) shards in-place semantics of ReconstructData /
    Reconstruct (cmd/erasure-coding.go:89,106).

    ``shards`` is a length k+m list; present shards are (n,) uint8 arrays.
    Returns a new list with missing entries filled (all of them, or data only).
    """
    total = data_blocks + parity_blocks
    if len(shards) != total:
        raise ValueError("wrong shard count")
    present = [i for i, s in enumerate(shards) if s is not None and len(s) > 0]
    if len(present) < data_blocks:
        raise ReconstructError(
            f"need {data_blocks} shards, have {len(present)}")
    if matrix is None:
        matrix = gf8.rs_matrix(data_blocks, total)

    n = len(shards[present[0]])
    rows = present[:data_blocks]
    sub = matrix[rows]  # (k, k)
    # decode matrix: inv(sub) maps the k surviving shards back to data shards
    dec = gf8.gf_mat_inv(sub)
    stack = np.stack([np.asarray(shards[i], dtype=np.uint8) for i in rows])
    out = list(shards)
    missing_data = [i for i in range(data_blocks)
                    if out[i] is None or len(out[i]) == 0]
    if missing_data:
        dec_rows = dec[missing_data]  # (md, k)
        rebuilt = gf8.gf_matmul(dec_rows, stack)
        for j, i in enumerate(missing_data):
            out[i] = rebuilt[j]
    if not data_only:
        missing_par = [i for i in range(data_blocks, total)
                       if out[i] is None or len(out[i]) == 0]
        if missing_par:
            # parity row applied to (possibly rebuilt) data shards
            data_stack = np.stack([np.asarray(out[i], dtype=np.uint8)
                                   for i in range(data_blocks)])
            par = gf8.gf_matmul(matrix[missing_par], data_stack)
            for j, i in enumerate(missing_par):
                out[i] = par[j]
    assert all(len(s) == n for s in out if s is not None and len(s) > 0)
    return out


def verify(shards: np.ndarray, data_blocks: int) -> bool:
    """reedsolomon Verify: recompute parity and compare."""
    parity = shards.shape[0] - data_blocks
    want = encode_parity(shards[:data_blocks], parity)
    return bool(np.array_equal(want, shards[data_blocks:]))
