"""Mesh codec backend — erasure matmuls sharded over the active device
mesh (parallel/mesh.py) behind the same impl surface as rs_kernels /
gf8_ref, so ``Erasure(backend="mesh")`` drops into the object layer's
existing PUT/GET/heal paths unchanged.

This is the multi-chip data plane the blueprint contracts (SURVEY.md
§2.3): encode fans the k shard blocks and GF(2) matrix columns across
the mesh's ``shard`` axis, partial products XOR-combine via one ICI
psum, stripes batch over the ``stripe`` axis — the device-native form
of the reference's goroutine-per-drive fan-out
(cmd/erasure-encode.go:36-70).  A 1-device mesh is the degenerate
single-chip case, so the backend is valid on any topology.

Shard math is bit-identical to the other backends: distributed_apply
zero-pads k up to the shard axis (a zero operand adds nothing to an
XOR fan-in) and this module zero-pads the stripe batch the same way.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from minio_tpu.parallel import mesh as mesh_mod
from . import gf8, rs_kernels


# version-compat shard_map resolution lives in parallel/mesh.py
_shard_map_fn = mesh_mod._shard_map


def _use_pallas() -> bool:
    """On TPU the per-device compute runs the fused pallas bitplane
    kernel (ops/rs_pallas.py, ~50 GiB/s/chip) with a ppermute ring
    XOR-combining the PACKED parity bytes — per-chip pallas speed,
    (S-1) x r x n bytes of ICI traffic (ring-allreduce optimal).  The
    XLA psum formulation stays as the portable path (CPU virtual mesh,
    and anywhere Mosaic is unavailable); MT_MESH_PALLAS=1/0 overrides
    for tests."""
    env = os.environ.get("MT_MESH_PALLAS", "")
    if env in ("0", "1"):
        return env == "1"
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


@functools.lru_cache(maxsize=64)
def _sharded_apply_pallas(mesh, r: int, kl: int, gs: int, tn: int,
                          interpret: bool):
    """shard_map'd per-device pallas matmul + packed-byte ring XOR.

    GF(2) addition of packed parity bytes IS XOR, so partial parities
    combine bitwise after each single-hop ppermute — no int32
    accumulator ever crosses ICI (a psum of the pre-packed accumulator
    would carry 32x the bytes and erase the kernel's HBM advantage).
    """
    import jax
    from jax.sharding import PartitionSpec as P
    from . import rs_pallas

    S = mesh.shape["shard"]
    perm = [(j, (j + 1) % S) for j in range(S)]

    def local(mats, data):
        # mats: (1, gs*8r, gs*8kl) int8 — this device's column slice;
        # data: (B/T, kl, n) uint8
        part = rs_pallas._gf2_apply_bm(mats[0], data,
                                       interpret=interpret,
                                       gs=gs, tn=tn)
        if S == 1:
            return part

        def step(_, acc):
            return jax.lax.ppermute(acc, "shard", perm) ^ part

        return jax.lax.fori_loop(0, S - 1, step, part)

    specs = dict(in_specs=(P("shard", None, None),
                           P("stripe", "shard", None)),
                 out_specs=P("stripe", None, None))
    smap = _shard_map_fn()
    try:
        fn = smap(local, mesh=mesh, check_vma=False, **specs)
    except TypeError:                      # older JAX spells it check_rep
        fn = smap(local, mesh=mesh, check_rep=False, **specs)
    return jax.jit(fn)


def _apply_pallas(m, rows: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """Mesh apply with the pallas per-device engine; pads B to the
    stripe x gs grid, k to the shard axis, n to the lane tile."""
    import jax
    import jax.numpy as jnp
    from . import rs_pallas

    T, S = m.shape["stripe"], m.shape["shard"]
    B, k, n = shards.shape
    r = rows.shape[0]
    padK = (-k) % S
    if padK:
        shards = np.concatenate(
            [shards, np.zeros((B, padK, n), np.uint8)], axis=1)
        rows = np.concatenate(
            [rows, np.zeros((r, padK), np.uint8)], axis=1)
    kl = (k + padK) // S
    gs = rs_pallas._GS
    padB = (-B) % (T * gs)
    if padB:
        shards = np.concatenate(
            [shards, np.zeros((padB, k + padK, n), np.uint8)])
    # same lane-tile heuristic as rs_pallas.apply_matrix
    q = max(n // 4, 1)
    tn = rs_pallas._LANES
    while tn * 2 <= q and tn < rs_pallas._TN:
        tn *= 2
    padN = (-n) % tn
    if padN:
        shards = np.pad(shards, ((0, 0), (0, 0), (0, padN)))
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    mats = jnp.stack([
        rs_pallas._device_matrix_bd(
            np.ascontiguousarray(rows[:, j * kl:(j + 1) * kl])
            .tobytes(), r, kl, gs)
        for j in range(S)])
    interpret = jax.default_backend() != "tpu"
    fn = _sharded_apply_pallas(m, r, kl, gs, tn, interpret)
    out = np.asarray(fn(mats, jnp.asarray(shards)))
    return out[:B, :, :n]


def apply_matrix(rows: np.ndarray, shards) -> np.ndarray:
    """out[b] = rows (GF) @ shards[b] over the active mesh.

    shards: (B, k, n) or (k, n) uint8.  B is zero-padded up to the
    stripe axis (zero stripes produce zero rows we slice off), so any
    batch size is valid on any mesh shape.
    """
    shards = np.asarray(shards, dtype=np.uint8)
    squeeze = shards.ndim == 2
    if squeeze:
        shards = shards[None]
    m = mesh_mod.get_active_mesh()
    if _use_pallas():
        rows8 = np.asarray(rows, dtype=np.uint8)
        out = _apply_pallas(m, rows8, shards)
        return out[0] if squeeze else out
    T = m.shape["stripe"]
    B = shards.shape[0]
    pad = (-B) % T
    if pad:
        shards = np.concatenate(
            [shards, np.zeros((pad,) + shards.shape[1:], np.uint8)])
    out = np.asarray(mesh_mod.distributed_apply(m, rows, shards))[:B]
    return out[0] if squeeze else out


def encode_parity(data_shards: np.ndarray, parity: int,
                  matrix: np.ndarray | None = None) -> np.ndarray:
    """(B, k, n) or (k, n) data -> (B, m, n) / (m, n) parity, sharded."""
    data_shards = np.asarray(data_shards, dtype=np.uint8)
    k = data_shards.shape[-2]
    if matrix is None:
        matrix = gf8.rs_matrix(k, k + parity)
    return apply_matrix(np.asarray(matrix)[k:], data_shards)


def reconstruct(shards, data_blocks: int, parity_blocks: int,
                data_only: bool = False,
                matrix: np.ndarray | None = None):
    """Single-stripe reconstruct; survivor/solve logic is shared with
    rs_kernels, only the matmul engine is mesh-sharded."""
    return rs_kernels.reconstruct(shards, data_blocks, parity_blocks,
                                  data_only=data_only, matrix=matrix,
                                  apply=apply_matrix)


def reconstruct_batch(shards: np.ndarray, present: list[int],
                      wanted: list[int], data_blocks: int,
                      parity_blocks: int,
                      matrix: np.ndarray | None = None) -> np.ndarray:
    """Batched same-pattern reconstruction over the mesh."""
    if matrix is None:
        matrix = gf8.rs_matrix(data_blocks, data_blocks + parity_blocks)
    rows = rs_kernels.decode_rows(matrix, data_blocks, list(present),
                                  list(wanted))
    return apply_matrix(rows, shards)


@functools.lru_cache(maxsize=64)
def _fused_pallas_single(mesh, r: int, kl: int, gs: int, bs: int,
                         S_h: int, pc: int, n_real: int, hp: bool,
                         interpret: bool):
    """Fused encode+bitrot through the SINGLE-kernel formulation
    (ops/rs_fused.py): per device the data tile crosses HBM once —
    parity is computed and hashed from the VMEM-resident tiles.  When
    k is sharded (S>1) the per-device parity is PARTIAL before the
    ring XOR, so the kernel hashes only the data lanes (hp=False) and
    the parity digests run post-ring on the small parity rows; a
    1-wide shard axis hashes everything in-kernel (hp=True)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from . import hh_pallas, rs_fused

    S = mesh.shape["shard"]
    perm = [(j, (j + 1) % S) for j in range(S)]

    def local(mats, data):
        import jax.numpy as jnp
        b = data.shape[0]
        part, planes = rs_fused._fused_call(
            mats[0], data, k=kl, ro=r, gs=gs, bs=bs, S=S_h, pc=pc,
            n_packets=n_real // 32, hash_parity=hp,
            interpret=interpret)
        if S > 1:
            def step(_, acc):
                return jax.lax.ppermute(acc, "shard", perm) ^ part
            parity = jax.lax.fori_loop(0, S - 1, step, part)
        else:
            parity = part
        digs = rs_fused._digests_from_planes(
            planes, data, part, k=kl, ro=r, bs=bs, S=S_h, B=b,
            n_real=n_real, hash_parity=hp)
        if hp:
            d_dig, p_dig = digs[:, :kl], digs[:, kl:]
        else:
            d_dig = digs
            rr = parity.shape[1]
            p_dig = hh_pallas.hh256_batch(
                parity[:, :, :n_real].reshape(b * rr, n_real)
            ).reshape(b, rr, 32)
        if S > 1:
            d_dig = jax.lax.all_gather(d_dig, "shard", axis=1,
                                       tiled=True)
        return parity, jnp.concatenate([d_dig, p_dig], axis=1)

    specs = dict(in_specs=(P("shard", None, None),
                           P("stripe", "shard", None)),
                 out_specs=(P("stripe", None, None),
                            P("stripe", None, None)))
    smap = _shard_map_fn()
    try:
        fn = smap(local, mesh=mesh, check_vma=False, **specs)
    except TypeError:
        fn = smap(local, mesh=mesh, check_rep=False, **specs)
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _fused_pallas(mesh, r: int, kl: int, gs: int, tn: int,
                  n_real: int, interpret: bool):
    """Fused encode+bitrot, pallas per-chip form: local pallas matmul
    on this device's k-slice, packed-byte ring XOR for the parity, and
    the pallas HighwayHash kernel over the UNPADDED shard widths
    (digests must never cover lane-tile padding); data digests ride an
    all_gather, parity digests compute post-ring on the replicated
    parity."""
    import jax
    from jax.sharding import PartitionSpec as P
    from . import hh_pallas, rs_pallas

    S = mesh.shape["shard"]
    perm = [(j, (j + 1) % S) for j in range(S)]

    def local(mats, data):
        b = data.shape[0]
        part = rs_pallas._gf2_apply_bm(mats[0], data,
                                       interpret=interpret,
                                       gs=gs, tn=tn)
        if S > 1:
            def step(_, acc):
                return jax.lax.ppermute(acc, "shard", perm) ^ part
            parity = jax.lax.fori_loop(0, S - 1, step, part)
        else:
            parity = part
        d_dig = hh_pallas.hh256_batch(
            data[:, :, :n_real].reshape(b * kl, n_real)
        ).reshape(b, kl, 32)
        if S > 1:
            d_dig = jax.lax.all_gather(d_dig, "shard", axis=1,
                                       tiled=True)
        rr = parity.shape[1]
        p_dig = hh_pallas.hh256_batch(
            parity[:, :, :n_real].reshape(b * rr, n_real)
        ).reshape(b, rr, 32)
        import jax.numpy as jnp
        return parity, jnp.concatenate([d_dig, p_dig], axis=1)

    specs = dict(in_specs=(P("shard", None, None),
                           P("stripe", "shard", None)),
                 out_specs=(P("stripe", None, None),
                            P("stripe", None, None)))
    smap = _shard_map_fn()
    try:
        fn = smap(local, mesh=mesh, check_vma=False, **specs)
    except TypeError:
        fn = smap(local, mesh=mesh, check_rep=False, **specs)
    return jax.jit(fn)


# single-kernel formulation state: None = untried, False = failed once
# (a Mosaic rejection must not re-pay compile latency per dispatch —
# the two-kernel pipeline below stays the proven fallback)
_SINGLE_STATE: dict = {"ok": None}


def _use_single() -> bool:
    env = os.environ.get("MT_FUSED_SINGLE", "")
    if env in ("0", "1"):
        return env == "1"
    return _SINGLE_STATE["ok"] is not False


def _encode_with_bitrot_single(m, data_blocks: int, parity_blocks: int,
                               blocks: np.ndarray):
    """encode_with_bitrot through ops/rs_fused.py: ONE kernel per
    device reads the data tile from HBM once and emits parity AND
    hash-state planes; padding mirrors _encode_with_bitrot_pallas
    (k up to the shard axis, B up to stripe x row-block, n up to the
    plan's lane tile)."""
    import jax
    import jax.numpy as jnp
    from . import rs_fused, rs_pallas

    T, S = m.shape["stripe"], m.shape["shard"]
    B, k, n = blocks.shape
    r = parity_blocks
    M = np.asarray(gf8.rs_matrix(data_blocks,
                                 data_blocks + parity_blocks))[k:]
    padK = (-k) % S
    if padK:
        blocks = np.concatenate(
            [blocks, np.zeros((B, padK, n), np.uint8)], axis=1)
        M = np.concatenate([M, np.zeros((r, padK), np.uint8)], axis=1)
    kl = (k + padK) // S
    hp = S == 1                     # full parity only without k-sharding
    p = rs_fused.plan(-(-B // T), kl, r, n, hash_parity=hp)
    B_pad = T * p["B_pad"]
    if B_pad != B:
        blocks = np.concatenate(
            [blocks, np.zeros((B_pad - B, k + padK, n), np.uint8)])
    if p["n_pad"] != n:
        blocks = np.pad(blocks, ((0, 0), (0, 0), (0, p["n_pad"] - n)))
    M = np.ascontiguousarray(M, dtype=np.uint8)
    mats = jnp.stack([
        rs_pallas._device_matrix_bd(
            np.ascontiguousarray(M[:, j * kl:(j + 1) * kl]).tobytes(),
            r, kl, p["gs"])
        for j in range(S)])
    interpret = jax.default_backend() != "tpu"
    fn = _fused_pallas_single(m, r, kl, p["gs"], p["bs"], p["S"],
                              p["pc"], n, hp, interpret)
    parity, digests = fn(mats, jnp.asarray(blocks))
    parity = np.asarray(parity)[:B, :, :n]
    digests = np.asarray(digests)
    # digest rows: [k+padK data slots][r parity slots] — drop the pads
    digests = np.concatenate(
        [digests[:B, :k], digests[:B, k + padK:]], axis=1)
    return parity, digests


def _encode_with_bitrot_pallas(m, data_blocks: int, parity_blocks: int,
                               blocks: np.ndarray):
    import jax
    import jax.numpy as jnp
    from . import rs_pallas

    T, S = m.shape["stripe"], m.shape["shard"]
    B, k, n = blocks.shape
    r = parity_blocks
    M = np.asarray(gf8.rs_matrix(data_blocks,
                                 data_blocks + parity_blocks))[k:]
    padK = (-k) % S
    if padK:
        blocks = np.concatenate(
            [blocks, np.zeros((B, padK, n), np.uint8)], axis=1)
        M = np.concatenate([M, np.zeros((r, padK), np.uint8)], axis=1)
    kl = (k + padK) // S
    gs = rs_pallas._GS
    padB = (-B) % (T * gs)
    if padB:
        blocks = np.concatenate(
            [blocks, np.zeros((padB, k + padK, n), np.uint8)])
    q = max(n // 4, 1)
    tn = rs_pallas._LANES
    while tn * 2 <= q and tn < rs_pallas._TN:
        tn *= 2
    padN = (-n) % tn
    if padN:
        blocks = np.pad(blocks, ((0, 0), (0, 0), (0, padN)))
    M = np.ascontiguousarray(M, dtype=np.uint8)
    mats = jnp.stack([
        rs_pallas._device_matrix_bd(
            np.ascontiguousarray(M[:, j * kl:(j + 1) * kl]).tobytes(),
            r, kl, gs)
        for j in range(S)])
    interpret = jax.default_backend() != "tpu"
    fn = _fused_pallas(m, r, kl, gs, tn, n, interpret)
    parity, digests = fn(mats, jnp.asarray(blocks))
    parity = np.asarray(parity)[:B, :, :n]
    digests = np.asarray(digests)
    # digest rows: [k+padK data slots][r parity slots] — drop the pads
    digests = np.concatenate(
        [digests[:B, :k], digests[:B, k + padK:]], axis=1)
    return parity, digests


def encode_with_bitrot(data_blocks: int, parity_blocks: int,
                       blocks: np.ndarray):
    """(parity, digests) for a (B, k, n) stripe batch through the FUSED
    sharded pipeline: each device encodes its partial parity and hashes
    its own shard slice; digests ride an all_gather.

    Two engines, same contract as apply_matrix: on TPU (or
    MT_MESH_PALLAS=1) the per-device compute is the pallas matmul +
    pallas HighwayHash with a packed-byte ppermute-ring XOR; elsewhere
    the XLA psum formulation (mesh.distributed_encode_with_bitrot).

    Pads B up to the stripe axis and k up to the shard axis (padded
    shards are zero; their digests are computed but sliced off).
    Returns (parity (B, m, n) uint8, digests (B, k+m, 32) uint8).
    """
    m = mesh_mod.get_active_mesh()
    blocks = np.asarray(blocks, dtype=np.uint8)
    if _use_pallas():
        if _use_single():
            try:
                out = _encode_with_bitrot_single(
                    m, data_blocks, parity_blocks, blocks)
                _SINGLE_STATE["ok"] = True
                return out
            except Exception as e:  # noqa: BLE001 — two-kernel fallback
                if _SINGLE_STATE["ok"] is None:
                    import sys
                    print(f"rs_mesh: single-kernel fused path failed "
                          f"({type(e).__name__}: {e}); using the "
                          f"two-kernel pipeline", file=sys.stderr)
                _SINGLE_STATE["ok"] = False
        return _encode_with_bitrot_pallas(
            m, data_blocks, parity_blocks, blocks)
    T, S = m.shape["stripe"], m.shape["shard"]
    B, k, n = blocks.shape
    padB, padK = (-B) % T, (-k) % S
    if padB or padK:
        padded = np.zeros((B + padB, k + padK, n), np.uint8)
        padded[:B, :k] = blocks
        blocks = padded
    M = gf8.rs_matrix(data_blocks, data_blocks + parity_blocks)
    Mp = np.asarray(M)[data_blocks:]              # (m, k)
    if padK:
        Mp = np.concatenate(
            [Mp, np.zeros((Mp.shape[0], padK), np.uint8)], axis=1)
    import jax.numpy as jnp
    M2 = jnp.asarray(gf8.gf2_expand(Mp), jnp.int8)
    fn = mesh_mod._fused_encode_hash(m, M2.shape[0], blocks.shape[1])
    parity, digests = fn(M2, jnp.asarray(blocks))
    parity = np.asarray(parity)[:B]
    digests = np.asarray(digests)
    # digest rows: [k+padK data slots][m parity slots] — drop the pads
    digests = np.concatenate([digests[:B, :k], digests[:B, k + padK:]],
                             axis=1)
    return parity, digests


def _encode_with_bitrot_batched(data_blocks: int, parity_blocks: int,
                                block_size: int,
                                blocks: np.ndarray):
    """encode_with_bitrot through the cross-request batcher when it is
    enabled: concurrent PUT streams' fused encode+digest dispatches
    coalesce into one padded shard_map dispatch over the shared mesh
    (the production mesh PUT path's ride onto parallel/batcher.py).
    The executor is per-stripe independent along the batch axis —
    parity rows and per-shard digests each depend only on their own
    stripe — so concatenation is bit-identical to dispatching apart."""
    try:
        from minio_tpu.parallel import batcher as _bt
        enabled = _bt.CONFIG.on()
    except Exception:  # pragma: no cover — parallel plane unavailable
        enabled = False
    if not enabled:
        return encode_with_bitrot(data_blocks, parity_blocks, blocks)
    codec = _bt.codec_for(data_blocks, parity_blocks, block_size,
                          "mesh")
    rows = np.asarray(gf8.rs_matrix(
        data_blocks, data_blocks + parity_blocks))[data_blocks:]
    return _bt.GLOBAL.submit(
        codec, "encode-bitrot", rows, blocks,
        fn=lambda _rows, cat: encode_with_bitrot(
            data_blocks, parity_blocks, cat))


def encode_object_framed_fused(data_blocks: int, parity_blocks: int,
                               block_size: int, data,
                               digest: int = 32) -> np.ndarray:
    """Whole object -> bitrot-framed shard files with parity AND digests
    from the fused mesh pipeline (the multi-chip form of
    Erasure.encode_object_framed + fill_framed).

    Returns (k+m, framed_len) uint8: per erasure block a
    [32B HighwayHash-256 digest][shard payload] frame, bit-identical to
    the host streaming-bitrot layout (cmd/bitrot-streaming.go framing
    around cmd/erasure-encode.go blocks).
    """
    k, m_par = data_blocks, parity_blocks
    buf = np.frombuffer(bytes(data), dtype=np.uint8) \
        if not isinstance(data, np.ndarray) \
        else np.asarray(data, np.uint8).ravel()
    total = buf.size
    bs = block_size
    ssize = gf8.shard_size(bs, k)
    nfull, tail_len = divmod(total, bs)
    tail_ss = gf8.ceil_frac(tail_len, k)
    F = digest + ssize
    flen = nfull * F + ((digest + tail_ss) if tail_len else 0)
    out = np.zeros((k + m_par, flen), dtype=np.uint8)
    if nfull:
        blocks = np.zeros((nfull, k, ssize), dtype=np.uint8)
        blocks.reshape(nfull, k * ssize)[:, :bs] = \
            buf[:nfull * bs].reshape(nfull, bs)
        parity, digs = _encode_with_bitrot_batched(k, m_par, block_size,
                                                   blocks)
        fview = out[:, :nfull * F].reshape(k + m_par, nfull, F)
        fview[:k, :, digest:] = blocks.transpose(1, 0, 2)
        fview[k:, :, digest:] = parity.transpose(1, 0, 2)
        fview[:, :, :digest] = digs.transpose(1, 0, 2)
    if tail_len:
        tblock = np.zeros((1, k, tail_ss), dtype=np.uint8)
        tblock.reshape(1, k * tail_ss)[0, :tail_len] = buf[nfull * bs:]
        parity_t, digs_t = _encode_with_bitrot_batched(
            k, m_par, block_size, tblock)
        base = nfull * F
        out[:k, base + digest:] = tblock[0]
        out[k:, base + digest:] = parity_t[0]
        out[:, base:base + digest] = digs_t[0]
    return out
