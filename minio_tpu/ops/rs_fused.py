"""Encode + HighwayHash-256 as ONE Pallas TPU kernel — the hash rides
encode's VMEM tiles (ISSUE 12 tentpole a).

The two-kernel fused pipeline (rs_pallas matmul, then hh_pallas over
data AND parity) moves every data byte across HBM twice: once into the
encode kernel, once into the hash kernel — 2D+2P of HBM traffic for an
operation whose information-theoretic minimum is D in + P out.  That
tax is the measured 38% gap between fused (32.12 GiB/s) and plain
encode (51.95, BENCH_r05).

This kernel closes the loop: per grid step the data tile is read from
HBM ONCE, the parity tile is computed on the MXU (rs_pallas's
unpack -> block-diagonal bit-matrix matmul -> pack, verbatim), and the
HighwayHash prologue then consumes BOTH tiles while they are still
VMEM-resident — the byte-plane transpose (hh_pallas's in-VMEM
prologue) runs over the concatenated data+parity sublanes, and the
packet chain updates a 32-limb state scratch carried across the
lane-tile grid dimension.  HBM sees D in and P out, nothing else.

Geometry: one grid row-block holds ``bs`` stripes x (k+ro) shards
flattened into S x 128 hash lanes (data shards stripe-major first,
then parity, then pad lanes whose garbage state is sliced off on the
way out).  For the headline 12+4 config that is 64 stripes = 1024
lanes = full (8, 128) VPU tiles — the same per-byte hash cost as the
standalone hh_pallas kernel, so the win is pure HBM traffic.

``hash_parity=False`` hashes only the data lanes: the mesh data plane
needs this when k is sharded across chips (per-device parity is
PARTIAL before the ring XOR — hashing it would digest garbage); the
full-parity hash then runs post-ring on the small parity rows.

Digests are bit-identical to the host HighwayHash-256 with the bitrot
magic key (tests/test_fused_kernel.py pins ragged geometries, tails
and the k/m matrix from the BASELINE configs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf8, hh_pallas as hhp, hh_kernels as hk, rs_pallas

_U32 = jnp.uint32
# lane-tile ceiling: 2048 bytes = 64 packets per chunk, the same
# packet-chunk size hh_pallas settled on (_PC_NAT) — large enough to
# amortise the transpose, small enough that data tile + tbuf + parity
# + state stay well under the 16 MiB scoped-vmem limit at bs=64
_TN_MAX = 2048


def plan(B: int, k: int, ro: int, n: int,
         hash_parity: bool = True) -> dict:
    """Tile plan for a (B, k, n) stripe batch: stripes per row-block
    (bs, a gs multiple), hash-lane rows S, lane tile tn — sized so the
    hash lanes fill (S, 128) tiles without padding a small batch up to
    a huge one.  Raises ValueError when the geometry cannot fit (one
    stripe's shards exceed 1024 lanes)."""
    R = k + (ro if hash_parity else 0)
    if B < 1 or n < 1:
        raise ValueError(f"degenerate batch ({B}, {n})")
    if R > 1024:
        raise ValueError(f"{R} shards/stripe exceed one row-block")
    stripes_cap = max(1, 1024 // R)
    gs = rs_pallas._GS if min(B, stripes_cap) >= rs_pallas._GS else 1
    bpad0 = -(-B // gs) * gs
    bs = min(max(gs, (stripes_cap // gs) * gs), bpad0)
    B_pad = -(-bpad0 // bs) * bs
    S = -(-bs * R // 128)
    tn = min(_TN_MAX, -(-n // 256) * 256)
    n_pad = -(-n // tn) * tn
    return {"R": R, "gs": gs, "bs": bs, "B_pad": B_pad, "S": S,
            "tn": tn, "n_pad": n_pad, "pc": tn // 32}


def _kernel(m_ref, in_ref, par_ref, dig_ref, st, tbuf, *, k: int,
            ro: int, gs: int, bs: int, S: int, pc: int,
            n_packets: int, hash_parity: bool, init_consts):
    """One (stripe-block, lane-tile) grid step.

    m_ref:  (gs*8*ro, gs*8*k) int8 block-diagonal bit-major matrix
    in_ref: (bs, k, tn) uint8 data; par_ref: (bs, ro, tn) uint8 out
    dig_ref:(1, 32, S, 128) u32 hash-state planes (written at last j)
    st:     VMEM (32, S, 128) u32 carried state
    tbuf:   VMEM (tn, S, 128) u8 byte-plane transpose staging
    """
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        for idx, c in enumerate(init_consts):
            st[idx] = jnp.full((S, 128), np.uint32(c), _U32)

    # -- encode: rs_pallas._kernel verbatim, looped over gs-stripe
    # sub-groups (the block-diagonal matrix packs gs stripes per MXU
    # call; bs/gs calls cover the row-block)
    par_vals = []
    for g in range(bs // gs):
        planes = []
        for s in range(gs):
            x = in_ref[g * gs + s].astype(jnp.int32)
            planes.extend(x >> b for b in range(8))
        bits = jnp.concatenate(planes, axis=0).astype(jnp.int8)
        acc = jax.lax.dot_general(
            m_ref[:], bits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        acc = acc & 1
        for s in range(gs):
            base = s * 8 * ro
            out = acc[base:base + ro]
            for b in range(1, 8):
                out = out | (acc[base + b * ro:base + (b + 1) * ro] << b)
            out = out.astype(jnp.uint8)
            par_ref[g * gs + s] = out
            par_vals.append(out)

    # -- hash prologue: byte-plane transpose of the VMEM-resident
    # tiles (data, and the parity values just computed when
    # hash_parity — the in-register copies, not a read-back of the
    # output ref) — the operand never revisits HBM, which is the point
    tn = pc * 32
    parts = [in_ref[:].reshape(bs * k, tn)]
    lanes_used = bs * k
    if hash_parity:
        parts.extend(par_vals)
        lanes_used += bs * ro
    if S * 128 - lanes_used:
        parts.append(jnp.zeros((S * 128 - lanes_used, tn), jnp.uint8))
    allb = parts[0] if len(parts) == 1 else \
        jnp.concatenate(parts, axis=0)
    tbuf[:] = jnp.swapaxes(allb, 0, 1).reshape(tn, S, 128)

    carry0 = tuple(st[idx] for idx in range(32))

    def body(p, carry):
        x = tbuf[pl.ds(p * 32, 32)].astype(_U32)     # (32, S, 128)
        lanes = []
        for lane in range(4):
            b = 8 * lane
            lo = (x[b] | (x[b + 1] << 8) | (x[b + 2] << 16)
                  | (x[b + 3] << 24))
            hi = (x[b + 4] | (x[b + 5] << 8) | (x[b + 6] << 16)
                  | (x[b + 7] << 24))
            lanes.append((hi, lo))
        return tuple(hhp._flatten(hhp._update_lanes(
            hhp._unflatten(list(carry)), lanes)))

    # tail lane-tiles may hold 0..pc whole packets of the real width;
    # the loop BOUND masks them (hh_pallas discipline — masking the 32
    # carried planes per packet measured 8.5x the update itself)
    valid = jnp.maximum(0, jnp.minimum(pc, n_packets - j * pc))
    final = jax.lax.fori_loop(0, valid, body, carry0)
    for idx in range(32):
        st[idx] = final[idx]

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        for idx in range(32):
            dig_ref[0, idx] = st[idx]


@functools.partial(jax.jit, static_argnames=(
    "k", "ro", "gs", "bs", "S", "pc", "n_packets", "hash_parity",
    "interpret"))
def _fused_call(mat_bd, data, *, k: int, ro: int, gs: int, bs: int,
                S: int, pc: int, n_packets: int, hash_parity: bool,
                interpret: bool):
    """data: (B_pad, k, n_pad) uint8, B_pad % bs == 0, n_pad % tn == 0
    (caller pads).  Returns (parity (B_pad, ro, n_pad) u8,
    planes (B_pad//bs, 32, S, 128) u32 hash-state limbs)."""
    Bp, _, npad = data.shape
    tn = pc * 32
    kernel = functools.partial(
        _kernel, k=k, ro=ro, gs=gs, bs=bs, S=S, pc=pc,
        n_packets=n_packets, hash_parity=hash_parity,
        init_consts=hhp._init_consts())
    return pl.pallas_call(
        kernel,
        grid=(Bp // bs, npad // tn),
        in_specs=[
            pl.BlockSpec((gs * 8 * ro, gs * 8 * k), lambda i, j: (0, 0)),
            pl.BlockSpec((bs, k, tn), lambda i, j: (i, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bs, ro, tn), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, 32, S, 128), lambda i, j: (i, 0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bp, ro, npad), jnp.uint8),
            jax.ShapeDtypeStruct((Bp // bs, 32, S, 128), _U32),
        ],
        scratch_shapes=[pltpu.VMEM((32, S, 128), _U32),
                        pltpu.VMEM((tn, S, 128), jnp.uint8)],
        interpret=interpret,
    )(mat_bd, data)


def _digests_from_planes(planes, data, parity, *, k: int, ro: int,
                         bs: int, S: int, B: int, n_real: int,
                         hash_parity: bool):
    """Hash-state planes -> per-shard digests (B, R, 32), pure jnp
    (shard_map-traceable).  Lane order inside a row-block is data
    stripe-major, then parity, then pad — undone here; the sub-packet
    remainder and finalization reuse the hh_kernels host-formulation
    (both are jnp over the (lanes, 4) limb state)."""
    NB = planes.shape[0]
    R = k + (ro if hash_parity else 0)
    limbs = []
    for idx in range(32):
        lane_flat = planes[:, idx].reshape(NB, S * 128)
        d = lane_flat[:, :bs * k].reshape(NB * bs, k)[:B]
        if hash_parity:
            p = lane_flat[:, bs * k:bs * R].reshape(NB * bs, ro)[:B]
            d = jnp.concatenate([d, p], axis=1)
        limbs.append(d.reshape(B * R))
    state = hhp._unflatten(limbs)
    st8 = []
    for v in hhp._VARS:
        for part in (0, 1):                      # hi then lo
            st8.append(jnp.stack([state[v][lane][part]
                                  for lane in range(4)], axis=-1))
    state8 = tuple(st8)
    rem = n_real % 32
    if rem:
        P = n_real // 32
        tails = [data[:B, :, P * 32:n_real]]
        if hash_parity:
            tails.append(parity[:B, :, P * 32:n_real])
        rb = (tails[0] if len(tails) == 1 else
              jnp.concatenate(tails, axis=1)).reshape(B * R, rem)
        state8 = hk._remainder_update(state8, rb, rem)
    return hhp._finalize(state8).reshape(B, R, 32)


def encode_hash_device(M: np.ndarray, shards, *, n_real: int | None
                       = None, hash_parity: bool = True,
                       interpret: bool | None = None):
    """Single-kernel fused encode+hash; returns DEVICE arrays
    (parity (B, ro, n), digests (B, R, 32)) so callers chain further
    device work without a host round trip.

    M: (ro, k) GF coefficients; shards: (B, k, n) uint8; digests cover
    ``n_real`` bytes per shard (default n — callers whose width is
    lane-padded pass the true shard width).
    """
    M = np.ascontiguousarray(M, dtype=np.uint8)
    shards = jnp.asarray(shards, jnp.uint8)
    B, k, n = shards.shape
    ro = M.shape[0]
    n_real = n if n_real is None else n_real
    p = plan(B, k, ro, n, hash_parity)
    if p["B_pad"] != B:
        shards = jnp.pad(shards, ((0, p["B_pad"] - B), (0, 0), (0, 0)))
    if p["n_pad"] != n:
        shards = jnp.pad(shards, ((0, 0), (0, 0), (0, p["n_pad"] - n)))
    mb = rs_pallas._device_matrix_bd(M.tobytes(), ro, k, p["gs"])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    parity, planes = _fused_call(
        mb, shards, k=k, ro=ro, gs=p["gs"], bs=p["bs"], S=p["S"],
        pc=p["pc"], n_packets=n_real // 32, hash_parity=hash_parity,
        interpret=interpret)
    digests = _digests_from_planes(
        planes, shards, parity, k=k, ro=ro, bs=p["bs"], S=p["S"], B=B,
        n_real=n_real, hash_parity=hash_parity)
    return parity[:B, :, :n], digests


def encode_with_bitrot_fused(data_blocks: int, parity_blocks: int,
                             blocks: np.ndarray,
                             matrix: np.ndarray | None = None,
                             interpret: bool | None = None):
    """rs_mesh.encode_with_bitrot's (parity, digests) contract through
    the single fused kernel — host numpy in, host numpy out, digests
    (B, k+m, 32) with data rows first."""
    blocks = np.asarray(blocks, dtype=np.uint8)
    if matrix is None:
        matrix = gf8.rs_matrix(data_blocks,
                               data_blocks + parity_blocks)
    rows = np.asarray(matrix)[data_blocks:]
    parity, digests = encode_hash_device(
        rows, blocks, hash_parity=True, interpret=interpret)
    return np.asarray(parity), np.asarray(digests)
