"""Erasure codec facade — the TPU-native counterpart of MinIO's ``Erasure``.

API mirrors cmd/erasure-coding.go:28-143 (NewErasure/EncodeData/
DecodeDataBlocks/DecodeDataAndParityBlocks/ShardSize/ShardFileSize/
ShardFileOffset) with a pluggable backend:

  * ``numpy`` — pure-host reference path (always available, conformance oracle)
  * ``tpu``   — batched bitplane MXU matmuls (rs_kernels.py), one chip
  * ``mesh``  — matmuls sharded over the active jax.sharding.Mesh with
                ICI XOR fan-in (rs_mesh.py); 1-device mesh = single chip
  * ``auto``  — tpu when an accelerator backend is initialized, else numpy

Shard layout, padding, and matrix construction are bit-identical between
backends (and with klauspost/reedsolomon's defaults).
"""

from __future__ import annotations

import functools
import time

import numpy as np

from ..obs import trace as _obstrace
from . import gf8, gf8_ref

MAX_SHARDS = 256  # data+parity <= 256 (cmd/erasure-coding.go:41)


def _nbytes(x) -> int:
    n = getattr(x, "nbytes", None)
    if n is not None:
        return int(n)
    try:
        return len(x)
    except TypeError:
        return 0


class ErasureError(ValueError):
    pass


@functools.lru_cache(maxsize=1)
def _accelerator_present() -> bool:
    try:
        import jax
        return jax.default_backend() != "cpu"
    except Exception:  # pragma: no cover
        return False


def _batcher(codec: "Erasure"):
    """The cross-request combining batcher (parallel/batcher.py) when
    enabled AND the codec dispatches to a device (tpu/mesh) — batching
    amortizes per-dispatch launch cost, which the numpy host path does
    not have: its GIL-releasing native matmuls already run in parallel
    across caller threads, and funneling them through one combiner
    would serialize them for nothing.  Lazy import both ways: a bare
    codec must not pull the parallel package at import time, and the
    batcher's bucket executors call back into
    ``Erasure._apply_matrix`` directly (the serial engine), so routing
    here can never recurse."""
    if not codec.is_device:
        return None
    try:
        from ..parallel import batcher as _b
    except Exception:  # pragma: no cover — parallel plane unavailable
        return None
    return _b.GLOBAL if _b.CONFIG.on() else None


class Erasure:
    """Erasure coding details for one (k, m, blockSize) geometry."""

    def __init__(self, data_blocks: int, parity_blocks: int,
                 block_size: int, backend: str = "auto"):
        if data_blocks <= 0 or parity_blocks <= 0:
            raise ErasureError("invalid shard number")
        if data_blocks + parity_blocks > MAX_SHARDS:
            raise ErasureError("max shard number exceeded")
        self.data_blocks = data_blocks
        self.parity_blocks = parity_blocks
        self.block_size = int(block_size)
        if backend == "auto":
            backend = "tpu" if _accelerator_present() else "numpy"
        if backend not in ("numpy", "tpu", "mesh"):
            raise ErasureError(f"unknown backend {backend!r}")
        self.backend = backend
        # resolve the compute impl once; all modules expose the same
        # encode_parity/reconstruct surface
        if backend == "tpu":
            try:
                from . import rs_kernels as impl
            except ImportError as e:
                raise ErasureError(f"tpu backend unavailable: {e}") from e
        elif backend == "mesh":
            try:
                from . import rs_mesh as impl
            except ImportError as e:
                raise ErasureError(f"mesh backend unavailable: {e}") from e
        else:
            impl = gf8_ref
        self._impl = impl
        self.matrix = gf8.rs_matrix(data_blocks, data_blocks + parity_blocks)

    @property
    def is_device(self) -> bool:
        """True when the matmul engine dispatches to accelerator(s) and
        accepts batched (B, k, n) operands (tpu and mesh backends)."""
        return self.backend in ("tpu", "mesh")

    # -- kernel observability ----------------------------------------------

    def _observe(self, op: str, nbytes: int, t0_ns: int,
                 blocks: int = 0, error: str = "") -> None:
        """One erasure-kernel dispatch: always counted into the
        mt_tpu_* metric families (encode GiB/s falls out of
        bytes_total / kernel_seconds_sum — the BENCH trajectory numbers
        become scrapeable), and published as a ``tpu``-type span when a
        trace consumer is active.  Cost is three counter bumps against
        megabytes of GF(2^8) math — noise on this path."""
        # lazy import: the compute-kernel layer must not pull the admin
        # package in at import time (layering; a future admin->ops
        # import must not cycle)
        from ..admin import metrics as _metrics
        dt = time.monotonic_ns() - t0_ns
        labels = {"op": op, "backend": self.backend}
        m = _metrics.GLOBAL
        m.inc("mt_tpu_ops_total", labels)
        m.inc("mt_tpu_bytes_total", labels, float(nbytes))
        m.observe("mt_tpu_kernel_seconds", labels, dt / 1e9,
                  buckets=_metrics.KERNEL_BUCKETS)
        if blocks:
            m.observe("mt_tpu_batch_blocks", {"op": op}, float(blocks),
                      buckets=_metrics.BATCH_BUCKETS)
        if error:
            m.inc("mt_tpu_errors_total", labels)
        if _obstrace.active():
            _obstrace.publish_span(_obstrace.make_span(
                "tpu", f"tpu.{op}", start_ns=time.time_ns() - dt,
                duration_ns=dt,
                input_bytes=int(nbytes), error=error,
                detail={"op": op, "backend": self.backend,
                        "k": self.data_blocks, "m": self.parity_blocks,
                        "blockSize": self.block_size,
                        "blocks": blocks}))

    def apply_matrix(self, rows: np.ndarray, shards) -> np.ndarray:
        """rows (GF) @ shards through this codec's engine; accepts
        (k, n) or batched (B, k, n) on device backends.  When the
        cross-request batcher is enabled the dispatch rides its
        combining queue (GET reconstruction and heal stripes from
        concurrent requests coalesce); the observed wall time then
        includes the combining window."""
        t0 = time.monotonic_ns()
        err = ""
        try:
            b = _batcher(self)
            if b is not None:
                return b.apply(self, "reconstruct", rows, shards)
            return self._apply_matrix(rows, shards)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            self._observe("matmul", _nbytes(shards), t0, error=err)

    def _apply_matrix(self, rows: np.ndarray, shards) -> np.ndarray:
        impl_apply = getattr(self._impl, "apply_matrix", None)
        if impl_apply is not None:
            return impl_apply(rows, shards)
        shards = np.asarray(shards, dtype=np.uint8)
        if shards.ndim == 3:
            return np.stack([gf8.gf_matmul(rows, s) for s in shards])
        return gf8.gf_matmul(rows, shards)

    # -- coding ------------------------------------------------------------

    def _encode_parity_blocks(self, blocks: np.ndarray) -> np.ndarray:
        """(B, k, n) stripes -> (B, m, n) parity.  Routes through the
        shared cross-request batcher when enabled (concurrent PUTs'
        stripe batches coalesce into one padded device dispatch),
        otherwise the backend impl directly — bit-identical either way
        (stripes are batch-axis independent)."""
        b = _batcher(self)
        if b is not None:
            return b.apply(
                self, "encode",
                np.asarray(self.matrix)[self.data_blocks:], blocks)
        if self.is_device:
            return self._impl.encode_parity(
                blocks, self.parity_blocks, self.matrix)
        return np.stack([
            self._impl.encode_parity(blk, self.parity_blocks,
                                     self.matrix) for blk in blocks])

    def encode_data(self, data) -> list[np.ndarray]:
        """EncodeData (cmd/erasure-coding.go:70): split+encode one block.

        Returns k+m shards; empty input returns k+m empty shards.
        """
        buf = np.frombuffer(bytes(data), dtype=np.uint8) \
            if not isinstance(data, np.ndarray) else data
        if buf.size == 0:
            return [np.zeros(0, dtype=np.uint8)
                    for _ in range(self.data_blocks + self.parity_blocks)]
        data_shards = gf8.split(buf, self.data_blocks)
        par = self._encode_parity_blocks(data_shards[None])[0]
        return [data_shards[i] for i in range(self.data_blocks)] + \
               [par[i] for i in range(self.parity_blocks)]

    def _reconstruct(self, shards, data_only: bool):
        lens = {len(s) for s in shards if s is not None and len(s) > 0}
        if len(lens) > 1:
            raise ErasureError("shard size mismatch")
        t0 = time.monotonic_ns()
        err = ""
        try:
            b = _batcher(self)
            if b is not None:
                # shared survivor/solve logic (host) with the heavy
                # matmul routed through the combining queue: concurrent
                # decodes with the same missing pattern fuse into one
                # dispatch.  rs_kernels.reconstruct with a numpy apply
                # is bit-identical to gf8_ref.reconstruct (GF matrix
                # algebra is exact, so composed decode rows produce the
                # same bytes as decode-then-reencode).
                try:
                    from . import rs_kernels
                except ImportError:
                    b = None
                if b is not None:
                    return rs_kernels.reconstruct(
                        shards, self.data_blocks, self.parity_blocks,
                        data_only=data_only, matrix=self.matrix,
                        apply=lambda rows, surv: b.apply(
                            self, "decode", rows, surv))
            return self._impl.reconstruct(
                shards, self.data_blocks, self.parity_blocks,
                data_only=data_only, matrix=self.matrix)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            present = sum(_nbytes(s) for s in shards if s is not None)
            self._observe("decode", present, t0, error=err)

    def decode_data_blocks(self, shards) -> list[np.ndarray]:
        """DecodeDataBlocks (cmd/erasure-coding.go:89): rebuild data only.

        Mirrors the reference's zero check exactly (it breaks on the first
        empty shard, so the count is 0 or 1): with no shard missing it is a
        no-op; otherwise reconstruction runs and fails if fewer than k shards
        survive -- including the all-empty case, which must surface an error
        rather than silently serving a truncated object.
        """
        n_zero = 0
        for s in shards:
            if s is None or len(s) == 0:
                n_zero += 1
                break
        if n_zero == 0 or n_zero == len(shards):
            return list(shards)
        return self._reconstruct(shards, data_only=True)

    def decode_data_and_parity_blocks(self, shards) -> list[np.ndarray]:
        """DecodeDataAndParityBlocks (cmd/erasure-coding.go:106)."""
        return self._reconstruct(shards, data_only=False)

    # -- shard math (cmd/erasure-coding.go:115-143) ------------------------

    def shard_size(self) -> int:
        return gf8.shard_size(self.block_size, self.data_blocks)

    def shard_file_size(self, total_length: int) -> int:
        return gf8.shard_file_size(
            self.block_size, self.data_blocks, total_length)

    def shard_file_offset(self, start_offset: int, length: int,
                          total_length: int) -> int:
        return gf8.shard_file_offset(
            self.block_size, self.data_blocks,
            start_offset, length, total_length)

    # -- batched whole-object path (TPU fast path) -------------------------

    def encode_object(self, data) -> list[np.ndarray]:
        """Encode a whole object's worth of bytes into per-disk shard files.

        Streams the reference's block loop (cmd/erasure-encode.go:80-107) as
        ONE batched device dispatch over all full blocks plus one small
        dispatch for the tail block.  Returns k+m shard-file byte arrays whose
        concatenated per-block layout matches block-by-block encode_data.
        """
        t0 = time.monotonic_ns()
        err = ""
        total = _nbytes(data)
        try:
            return self._encode_object(data)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            self._observe("encode", total, t0,
                          blocks=-(-total // self.block_size)
                          if total else 0, error=err)

    def _encode_object(self, data) -> list[np.ndarray]:
        buf = np.frombuffer(bytes(data), dtype=np.uint8) \
            if not isinstance(data, np.ndarray) \
            else np.asarray(data, np.uint8).ravel()
        total = buf.size
        k, m = self.data_blocks, self.parity_blocks
        if total == 0:
            return [np.zeros(0, dtype=np.uint8) for _ in range(k + m)]
        bs = self.block_size
        ssize = self.shard_size()
        nfull = total // bs
        outs: list[list[np.ndarray]] = [[] for _ in range(k + m)]
        if nfull:
            blocks = buf[: nfull * bs].reshape(nfull, k, ssize) \
                if bs == k * ssize else None
            if blocks is None:
                # blockSize not divisible by k: per-block zero padding
                blocks = np.zeros((nfull, k, ssize), dtype=np.uint8)
                flat = buf[: nfull * bs].reshape(nfull, bs)
                blocks.reshape(nfull, k * ssize)[:, :bs] = flat
            par = self._encode_parity_blocks(blocks)
            for i in range(k):
                outs[i].append(np.ascontiguousarray(blocks[:, i]).reshape(-1))
            for j in range(m):
                outs[k + j].append(np.ascontiguousarray(par[:, j]).reshape(-1))
        tail = buf[nfull * bs:]
        if tail.size:
            for i, s in enumerate(self.encode_data(tail)):
                outs[i].append(s)
        return [np.concatenate(chunks) if len(chunks) != 1 else chunks[0]
                for chunks in outs]

    def speedtest(self, size: int = 8 << 20, iters: int = 3) -> dict:
        """Timed probe of this codec's hot paths (the admin
        ``speedtest-tpu`` leg): whole-object batched encode and
        worst-case reconstruction (all m parity shards consumed to
        rebuild m lost data shards), after one untimed warmup so
        device backends measure steady-state, not compile time.

        Dispatches ride the normal encode/decode paths, so the probe
        itself lands in mt_tpu_* metrics and ``tpu`` spans like any
        production traffic."""
        import os as _os
        iters = max(1, int(iters))
        size = max(1, int(size))
        data = np.frombuffer(_os.urandom(size), dtype=np.uint8)
        self.encode_object(data)                      # warmup/compile
        t0 = time.monotonic()
        for _ in range(iters):
            shards = self.encode_object(data)
        encode_s = max(time.monotonic() - t0, 1e-9)
        # per-block decode with the first m data shards lost
        block = data[:min(self.block_size, size)]
        block_shards = self.encode_data(block)
        lost = list(block_shards)
        for i in range(min(self.parity_blocks, self.data_blocks)):
            lost[i] = None
        nblocks = max(1, size // max(len(block), 1))
        self.decode_data_blocks(list(lost))           # warmup
        t0 = time.monotonic()
        for _ in range(iters * nblocks):
            self.decode_data_blocks(list(lost))
        decode_s = max(time.monotonic() - t0, 1e-9)
        del shards
        gib = 1 << 30
        return {
            "encodeGiBps": round(size * iters / encode_s / gib, 3),
            "decodeGiBps": round(
                len(block) * iters * nblocks / decode_s / gib, 3),
            "bytes": size,
            "iters": iters,
            "k": self.data_blocks,
            "m": self.parity_blocks,
            "blockSize": self.block_size,
            "backend": self.backend,
        }

    def framed_shape(self, total: int, digest: int = 32) -> tuple[int, int]:
        """Shape of encode_object_framed's output for a ``total``-byte
        object — lets the put pipeline acquire a recycled buffer
        (utils/bufpool.py) before encoding."""
        k, m = self.data_blocks, self.parity_blocks
        bs = self.block_size
        ssize = self.shard_size()
        nfull, tail_len = divmod(total, bs)
        tail_ss = gf8.ceil_frac(tail_len, k)
        F = digest + ssize
        flen = nfull * F + ((digest + tail_ss) if tail_len else 0)
        return (k + m, flen)

    def encode_object_framed(self, data, digest: int = 32,
                             out: np.ndarray | None = None) -> np.ndarray:
        """Encode a whole object straight into bitrot-framed shard files.

        Returns (k+m, framed_len) uint8 where each row is the final
        on-disk layout [digest-slot][block] per erasure block
        (cmd/bitrot-streaming.go framing around cmd/erasure-encode.go
        blocks).  Digest slots are left ZEROED for the caller to fill
        in place (hashing.highwayhash.hh256_fill).  One copy total:
        data bytes land once in their final frame position; parity is
        computed by the native kernel directly into its frame payloads.
        Requires the native GF8 library (callers fall back to
        encode_object + streaming framing)."""
        t0 = time.monotonic_ns()
        err = ""
        total = _nbytes(data)
        try:
            return self._encode_object_framed(data, digest, out)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            self._observe("encode-framed", total, t0,
                          blocks=-(-total // self.block_size)
                          if total else 0, error=err)

    def _encode_object_framed(self, data, digest: int = 32,
                              out: np.ndarray | None = None) -> np.ndarray:
        from . import gf8_native
        assert gf8_native.available()
        # zero-copy view: bytes AND memoryview slices (the put path
        # feeds whole-body memoryviews) frame without materializing
        buf = np.frombuffer(data, dtype=np.uint8) \
            if not isinstance(data, np.ndarray) \
            else np.asarray(data, np.uint8).ravel()
        total = buf.size
        k, m = self.data_blocks, self.parity_blocks
        bs = self.block_size
        ssize = self.shard_size()
        nfull, tail_len = divmod(total, bs)
        tail_ss = gf8.ceil_frac(tail_len, k)
        F = digest + ssize
        flen = nfull * F + ((digest + tail_ss) if tail_len else 0)
        # np.empty + targeted clears: every payload byte is overwritten
        # below (data copy / native parity matmul), so a full calloc
        # would memset ~6 MB per 4 MiB object only to overwrite it.
        # Only the digest slots and the short-row padding gaps need
        # zeroing (framing contract: digest filled later in place,
        # padding must be zero for bit-identical shard math).  A
        # recycled ``out`` (bufpool) relies on the same targeted
        # clears, so stale bytes from the previous batch never leak.
        if out is None or out.shape != (k + m, flen) \
                or out.dtype != np.uint8:
            out = np.empty((k + m, flen), dtype=np.uint8)
        if nfull:
            fview = out[:, :nfull * F].reshape(k + m, nfull, F)
            fview[:, :, :digest] = 0                  # digest slots
            for i in range(k):                        # short data rows
                ln = min(ssize, max(0, bs - i * ssize))
                if ln < ssize:
                    fview[i, :, digest + ln:] = 0
        if tail_len:
            out[:, nfull * F:] = 0                    # whole tail frame
        parity_rows = np.asarray(self.matrix)[k:]
        if nfull:
            src = buf[:nfull * bs].reshape(nfull, bs)
            dview = out[:, :nfull * F].reshape(k + m, nfull, F)
            for i in range(k):
                lo = i * ssize
                ln = min(ssize, bs - lo)
                dview[i, :, digest:digest + ln] = src[:, lo:lo + ln]
            if m:
                for b in range(nfull):
                    base = b * F + digest
                    gf8_native.matmul_into(
                        parity_rows, out[:k, base:base + ssize],
                        out[k:, base:base + ssize])
        if tail_len:
            base = nfull * F + digest
            tsrc = buf[nfull * bs:]
            for i in range(k):
                lo = i * tail_ss
                ln = max(0, min(tail_ss, tail_len - lo))
                if ln:
                    out[i, base:base + ln] = tsrc[lo:lo + ln]
            if m and tail_ss:
                gf8_native.matmul_into(
                    parity_rows, out[:k, base:base + tail_ss],
                    out[k:, base:base + tail_ss])
        return out
