"""GF(2^8) arithmetic core for the TPU-native Reed-Solomon erasure codec.

This is the host-side (numpy) foundation of the erasure-coding hot path.  The
reference implementation is MinIO's klauspost/reedsolomon dependency
(reference: cmd/erasure-coding.go:23,56) which itself ports the Backblaze
JavaReedSolomon field:

  * field GF(2^8) defined by the primitive polynomial x^8+x^4+x^3+x^2+1
    (0x11d), generator element 2,
  * systematic encode matrix built from a Vandermonde matrix made systematic
    by multiplying with the inverse of its top k x k square,
  * ``Split`` padding semantics (zero-pad the tail shard).

Everything here is pure numpy and bit-identical to the reference semantics;
the TPU kernels in rs_kernels.py consume the tables/matrices produced here.
"""

from __future__ import annotations

import functools

import numpy as np

FIELD_SIZE = 256
_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1, primitive; matches Backblaze/klauspost


def _build_tables() -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """exp/log tables for GF(2^8)/0x11d with generator 2.

    exp is doubled (510 entries) so exp[log[a]+log[b]] needs no modular
    reduction during multiply.
    """
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    log[0] = -255  # sentinel; callers must special-case zero
    # full 256x256 multiplication table (64KiB) -- handy for reference code
    a = np.arange(256)
    mul = np.zeros((256, 256), dtype=np.uint8)
    la = log[a]
    for i in range(1, 256):
        mul[i, 1:] = exp[(log[i] + la[1:])]
    return exp, log, mul


GF_EXP, GF_LOG, GF_MUL = _build_tables()

# inverse: a^-1 = exp[255 - log[a]]
GF_INV = np.zeros(256, dtype=np.uint8)
GF_INV[1:] = GF_EXP[255 - GF_LOG[np.arange(1, 256)]]


def gf_mul(a, b):
    """Elementwise GF(2^8) multiply of arrays/scalars (uint8)."""
    return GF_MUL[np.asarray(a, dtype=np.uint8), np.asarray(b, dtype=np.uint8)]


def gf_exp(a: int, n: int) -> int:
    """a**n in GF(2^8) (matches klauspost galExp)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


def gf_matmul_numpy(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Pure-numpy GF matmul — the conformance oracle the native and TPU
    paths are validated against (tables built in _build_tables above)."""
    A = np.asarray(A, dtype=np.uint8)
    B = np.asarray(B, dtype=np.uint8)
    r, k = A.shape
    k2, c = B.shape
    assert k == k2
    out = np.zeros((r, c), dtype=np.uint8)
    for i in range(k):  # k <= 256; columns vectorized
        prod = GF_MUL[A[:, i][:, None], B[i][None, :]]
        out ^= prod
    return out


def gf_matmul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """GF matrix multiply: (r,k) x (k,c) -> (r,c), XOR-accumulated.

    Dispatches to the native AVX2 kernel (native/gf8.cc — the host
    equivalent of klauspost/reedsolomon's assembly) for real shard
    widths; numpy handles tiny inputs and environments without g++.
    ctypes releases the GIL inside the native call, so concurrent PUT
    threads scale."""
    B = np.asarray(B)
    if B.ndim == 2 and B.shape[1] >= 1024:
        from . import gf8_native
        if gf8_native.available():
            return gf8_native.matmul(A, B)
    return gf_matmul_numpy(A, B)


def gf_mat_inv(M: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix via Gauss-Jordan.

    Raises ValueError on singular input (mirrors reedsolomon's
    ErrSingular -> reconstruction failure).
    """
    M = np.asarray(M, dtype=np.uint8)
    n = M.shape[0]
    assert M.shape == (n, n)
    aug = np.concatenate([M.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        # partial pivot: find a row with nonzero pivot
        pivot = None
        for r in range(col, n):
            if aug[r, col] != 0:
                pivot = r
                break
        if pivot is None:
            raise ValueError("singular matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = GF_INV[aug[col, col]]
        aug[col] = GF_MUL[np.full(2 * n, inv_p, dtype=np.uint8), aug[col]]
        for r in range(n):
            if r != col and aug[r, col] != 0:
                f = aug[r, col]
                aug[r] ^= GF_MUL[np.full(2 * n, f, dtype=np.uint8), aug[col]]
    return aug[:, n:].copy()


@functools.lru_cache(maxsize=None)
def _vandermonde(rows: int, cols: int) -> np.ndarray:
    vm = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            vm[r, c] = gf_exp(r, c)
    return vm


@functools.lru_cache(maxsize=None)
def rs_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """klauspost-compatible systematic encode matrix (total x data).

    vm = Vandermonde(total, data); M = vm @ inv(vm[:data,:data]).
    Top k rows are the identity; bottom m rows are the parity coefficients.
    Mirrors reedsolomon.buildMatrix (reference dep of cmd/erasure-coding.go:56).
    """
    vm = _vandermonde(total_shards, data_shards)
    top_inv = gf_mat_inv(vm[:data_shards, :data_shards])
    M = gf_matmul(vm, top_inv)
    M.setflags(write=False)
    return M


@functools.lru_cache(maxsize=None)
def cauchy_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """Cauchy-style systematic matrix (reedsolomon WithCauchyMatrix option)."""
    parity = total_shards - data_shards
    M = np.zeros((total_shards, data_shards), dtype=np.uint8)
    M[:data_shards] = np.eye(data_shards, dtype=np.uint8)
    for r in range(parity):
        for c in range(data_shards):
            # 1 / (x_r + y_c) with x_r = data+r, y_c = c
            M[data_shards + r, c] = GF_INV[(data_shards + r) ^ c]
    M.setflags(write=False)
    return M


# ---------------------------------------------------------------------------
# GF(2) bitplane expansion: the bridge from GF(2^8) coefficients to MXU matmuls
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _companion_cols() -> np.ndarray:
    """(256, 8, 8) lookup: companion bit-matrix for every GF coefficient.

    For coefficient c, B[c] is the 8x8 GF(2) matrix with
    out_bits = B[c] @ in_bits (mod 2), bits LSB-first:
    column j of B[c] = bits of (c * x^j) = bits of gf_mul(c, 1<<j).
    """
    out = np.zeros((256, 8, 8), dtype=np.uint8)
    for c in range(256):
        for j in range(8):
            v = int(GF_MUL[c, 1 << j])
            for i in range(8):
                out[c, i, j] = (v >> i) & 1
    return out


def gf2_expand(M: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) coefficient matrix (r,k) to its GF(2) form (8r,8k).

    parity_bits = expand(M) @ data_bits (mod 2) computes the same product as
    the GF(2^8) matrix-vector multiply -- this is what runs on the MXU.
    """
    M = np.asarray(M, dtype=np.uint8)
    r, k = M.shape
    comp = _companion_cols()[M]  # (r, k, 8, 8)
    return comp.transpose(0, 2, 1, 3).reshape(8 * r, 8 * k).copy()


# ---------------------------------------------------------------------------
# Shard-size math (bit-identical with cmd/erasure-coding.go:115-143)
# ---------------------------------------------------------------------------

def ceil_frac(numerator: int, denominator: int) -> int:
    """Bit-identical port of ceilFrac (cmd/utils.go:613-628).

    Go semantics: zero denominator returns 0; division truncates toward zero
    and only positive non-exact quotients are bumped up.
    """
    if denominator == 0:
        return 0
    if denominator < 0:
        numerator = -numerator
        denominator = -denominator
    ceil = abs(numerator) // denominator
    if numerator < 0:
        ceil = -ceil  # Go int division truncates toward zero
    if numerator > 0 and numerator % denominator != 0:
        ceil += 1
    return ceil


def shard_size(block_size: int, data_blocks: int) -> int:
    """cmd/erasure-coding.go:115 ShardSize."""
    return ceil_frac(block_size, data_blocks)


def shard_file_size(block_size: int, data_blocks: int, total_length: int) -> int:
    """cmd/erasure-coding.go:120 ShardFileSize."""
    if total_length == 0:
        return 0
    if total_length == -1:
        return -1
    num_shards = total_length // block_size
    last_block_size = total_length % block_size
    last_shard_size = ceil_frac(last_block_size, data_blocks)
    return num_shards * shard_size(block_size, data_blocks) + last_shard_size


def shard_file_offset(block_size: int, data_blocks: int, start_offset: int,
                      length: int, total_length: int) -> int:
    """cmd/erasure-coding.go:134 ShardFileOffset."""
    ssize = shard_size(block_size, data_blocks)
    sfsize = shard_file_size(block_size, data_blocks, total_length)
    end_shard = (start_offset + length) // block_size
    till_offset = end_shard * ssize + ssize
    if till_offset > sfsize:
        till_offset = sfsize
    return till_offset


def split(data: bytes | bytearray | memoryview | np.ndarray,
          data_shards: int) -> np.ndarray:
    """reedsolomon Split semantics: k equal shards, zero-padded tail.

    Returns a (data_shards, per_shard) uint8 array (data shards only).
    Raises ValueError on empty input (reedsolomon.ErrShortData).
    """
    buf = np.frombuffer(bytes(data), dtype=np.uint8) if not isinstance(data, np.ndarray) \
        else data.astype(np.uint8, copy=False).ravel()
    if buf.size == 0:
        raise ValueError("short data")
    per_shard = ceil_frac(buf.size, data_shards)
    out = np.zeros(data_shards * per_shard, dtype=np.uint8)
    out[: buf.size] = buf
    return out.reshape(data_shards, per_shard)
