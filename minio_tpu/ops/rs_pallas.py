"""Fused GF(2^8) Reed-Solomon coding as ONE Pallas TPU kernel.

The XLA formulation (rs_kernels._gf2_apply) materialises the GF(2) bit
planes in HBM: (B, k, n) bytes inflate to (B, 8k, n) int8 on the way in
and (8r, B, n) int32 on the way out — an 8x HBM traffic tax that leaves
the kernel HBM-bound at ~5% of chip roofline (BENCH_r02).

This kernel keeps bit planes VMEM-resident for their whole life:

    bytes in  --unpack-->  bit planes  --MXU matmul-->  parity bits
                                 --pack-->  parity bytes out

HBM sees only the byte tiles: k*TN in, r*TN out per grid step — the
information-theoretic minimum for the operation.

Layout trick: the expanded GF(2) matrix's rows/cols are permuted to
BIT-MAJOR order (plane b of shard s at row b*shards+s, vs gf2_expand's
shard-major s*8+b).  Bit-major makes the in-kernel unpack a plain
concatenate of 8 shifted copies along sublanes and the pack 8 static
sublane slices — both natively supported Mosaic ops — where shard-major
would need an 8-way interleave the hardware has no vector op for.

Same kernel serves encode (parity rows) and decode (inverted survivor
rows) exactly like rs_kernels; reference semantics per
cmd/erasure-coding.go:56-143 (klauspost/reedsolomon AVX2 hot loop).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from . import gf8

_LANES = 128
# lanes per grid step: large enough that the (8r, 8k) @ (8k, TN) matmul
# amortises grid/DMA overhead, small enough that lane padding on the
# 87382-byte headline shard size stays under ~5%
_TN = 4096
# stripes per grid step, packed block-diagonally into one matmul: a lone
# (32, 96) matrix wastes the 128x128 MXU tile on padding (32->128 rows,
# 96->128 contraction).  diag(E, E, E, E) is (128, 384): M fully used,
# K = 3 exact passes — 4/3 the slot efficiency, measured MXU-bound
_GS = 4


def expand_bitmajor(M: np.ndarray) -> np.ndarray:
    """GF(2^8) coefficient matrix (r, k) -> GF(2) matrix (8r, 8k) with
    BIT-MAJOR row/col order: row b*r+i computes bit b of out shard i from
    col planes b'*k+j (bit b' of in shard j)."""
    M = np.ascontiguousarray(M, dtype=np.uint8)
    r, k = M.shape
    E = gf8.gf2_expand(M)                      # (8r, 8k) shard-major
    return np.ascontiguousarray(
        E.reshape(r, 8, k, 8).transpose(1, 0, 3, 2).reshape(8 * r, 8 * k))


def _kernel(m_ref, in_ref, out_ref, *, k: int, ro: int, gs: int):
    """One (stripe-group, lane-tile) grid step, everything VMEM-resident.

    m_ref:  (gs*8*ro, gs*8*k) int8 block-diagonal bit-major matrix
    in_ref: (gs, k, TN) uint8 data shards for gs stripes
    out_ref:(gs, ro, TN) uint8 output shards
    """
    planes = []
    for s in range(gs):
        x = in_ref[s].astype(jnp.int32)        # (k, TN)
        # unpack LSB-first into bit-major planes: rows s*8k + b*k + j.
        # No & 1 mask: (x >> b) carries bits b..7 in positions 0..7-b,
        # but every bit above position 0 contributes an EVEN multiple to
        # the matmul accumulator, so the final `acc & 1` parity is
        # unaffected (and the int8 wrap subtracts multiples of 256 —
        # also even).  Halves the VPU unpack work.
        planes.extend(x >> b for b in range(8))
    bits = jnp.concatenate(planes, axis=0).astype(jnp.int8)
    acc = jax.lax.dot_general(                 # (gs*8*ro, TN) on MXU
        m_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    acc = acc & 1                              # parity == XOR over GF(2)
    for s in range(gs):
        base = s * 8 * ro
        out = acc[base:base + ro]
        for b in range(1, 8):
            out = out | (acc[base + b * ro:base + (b + 1) * ro] << b)
        out_ref[s] = out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret", "gs", "tn"))
def _gf2_apply_bm(matrix_bd: jax.Array, data: jax.Array,
                  interpret: bool = False, gs: int = _GS,
                  tn: int = _TN) -> jax.Array:
    """matrix_bd: (gs*8r, gs*8k) int8 block-diagonal bit-major; data:
    (B, k, n) uint8 with B a multiple of gs and n a multiple of tn
    (caller pads both).  Returns (B, r, n) uint8."""
    B, k, n = data.shape
    ro = matrix_bd.shape[0] // (8 * gs)
    kernel = functools.partial(_kernel, k=k, ro=ro, gs=gs)
    return pl.pallas_call(
        kernel,
        grid=(B // gs, n // tn),
        in_specs=[
            pl.BlockSpec((gs * 8 * ro, gs * 8 * k), lambda i, j: (0, 0)),
            pl.BlockSpec((gs, k, tn), lambda i, j: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((gs, ro, tn), lambda i, j: (i, 0, j)),
        out_shape=jax.ShapeDtypeStruct((B, ro, n), jnp.uint8),
        interpret=interpret,
    )(matrix_bd, data)


@functools.lru_cache(maxsize=256)
def _device_matrix_bd(key: bytes, rows: int, cols: int,
                      gs: int) -> jax.Array:
    """Block-diagonal bit-major expanded matrix, cached on device by
    content (bounded for the same reason as rs_kernels._device_matrix:
    decode matrices vary per survivor pattern)."""
    M = np.frombuffer(key, dtype=np.uint8).reshape(rows, cols)
    E = expand_bitmajor(M)
    R, K = E.shape
    bd = np.zeros((gs * R, gs * K), dtype=np.int8)
    for s in range(gs):
        bd[s * R:(s + 1) * R, s * K:(s + 1) * K] = E
    return jnp.asarray(bd)


def apply_matrix(M: np.ndarray, shards, *,
                 interpret: bool | None = None) -> jax.Array:
    """out[b] = M (GF) @ shards[b], fused pallas path.

    M: (r, k) uint8 GF coefficients; shards: (B, k, n) uint8 (device or
    host).  Returns a DEVICE array (B, r, n) — callers chain further
    device work (hashing, mixing) without a host round trip; np.asarray
    the result to land it.
    """
    M = np.ascontiguousarray(M, dtype=np.uint8)
    shards = jnp.asarray(shards, jnp.uint8)
    B, k, n = shards.shape
    bpad = (-B) % _GS
    if bpad and B > 1:                 # group to keep the MXU tile full
        shards = jnp.pad(shards, ((0, bpad), (0, 0), (0, 0)))
    gs = _GS if shards.shape[0] % _GS == 0 else 1
    mb = _device_matrix_bd(M.tobytes(), M.shape[0], M.shape[1], gs)
    # bucket the lane tile to ~n/4 so padding waste stays under ~25%
    # at every shard width (a 5462-byte shard must not pad 50% to 8192,
    # nor a 300-byte one 13x to 4096), capped at _TN for real widths
    q = max(n // 4, 1)
    tn = _LANES
    while tn * 2 <= q and tn < _TN:
        tn *= 2
    pad = (-n) % tn
    if pad:
        shards = jnp.pad(shards, ((0, 0), (0, 0), (0, pad)))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out = _gf2_apply_bm(mb, shards, interpret=interpret, gs=gs, tn=tn)
    if bpad and B > 1:
        out = out[:B]
    if pad:
        out = out[:, :, :n]
    return out
