"""S3 API HTTP server (cmd/api-router.go:82 + cmd/object-handlers.go /
cmd/bucket-handlers.go).

Path-style S3 over a threading HTTP server: the L1/L3 frontend of the
framework.  Handlers authenticate (SigV4 header or presigned), map the
route to an ObjectLayer call, and render S3 XML.  The compute-heavy body
(erasure encode/decode) happens inside the object layer on TPU.
"""

from __future__ import annotations

import datetime
import email.utils
import hashlib
import os
import re
import threading
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..objectlayer import interface as ol
from ..objectlayer.bucket_meta import BucketMetadataSys
from . import errors as s3err
from . import sigv4

MAX_OBJECT_SIZE = 5 * 1024 * 1024 * 1024 * 1024  # 5 TiB (docs/minio-limits.md)
MAX_PUT_SIZE = 5 * 1024 * 1024 * 1024   # single PUT / part (minio-limits:28)
# bodies above this stream straight into the object layer (O(batch) RSS);
# smaller ones take the simpler buffered path
STREAM_PUT_THRESHOLD = 8 * 1024 * 1024
S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"

_BUCKET_RE = re.compile(r"^[a-z0-9][a-z0-9.\-]{1,61}[a-z0-9]$")


class _BodyReader:
    """Bounded socket-body reader with optional integrity checks: caps
    reads at the declared Content-Length, raises IncompleteBody when the
    peer hangs up early, and verifies sha256/md5 digests at EOF — the
    hash.Reader analog (pkg/hash) that lets PUTs stream while keeping
    the commit gated on body integrity."""

    def __init__(self, raw, total: int, sha256_hex: str | None = None,
                 md5_digest: bytes | None = None):
        self.raw = raw
        self.remaining = total
        self._sha = hashlib.sha256() if sha256_hex else None
        self._want_sha = sha256_hex
        self._md5 = hashlib.md5() if md5_digest else None
        self._want_md5 = md5_digest

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self.remaining
        n = min(n, self.remaining)
        if n <= 0:
            return b""
        chunks = []
        while n > 0:
            c = self.raw.read(n)
            if not c:
                raise S3Error("IncompleteBody")
            chunks.append(c)
            n -= len(c)
            self.remaining -= len(c)
        data = chunks[0] if len(chunks) == 1 else b"".join(chunks)
        if self._sha is not None:
            self._sha.update(data)
        if self._md5 is not None:
            self._md5.update(data)
        if self.remaining == 0:
            if self._sha is not None and \
                    self._sha.hexdigest() != self._want_sha:
                raise S3Error("BadDigest")
            if self._md5 is not None and \
                    self._md5.digest() != self._want_md5:
                raise S3Error("BadDigest")
        return data

    def readline(self, limit: int = 8192) -> bytes:
        """Bounded readline for aws-chunked frame headers."""
        out = bytearray()
        while len(out) < limit and self.remaining > 0:
            c = self.raw.read(1)
            if not c:
                raise S3Error("IncompleteBody")
            self.remaining -= 1
            out += c
            if out.endswith(b"\r\n"):
                break
        return bytes(out)


class _MD5Reader:
    """Content-MD5 verification over an already-decoded stream (the
    aws-chunked plain view), checked at EOF before the commit."""

    def __init__(self, inner, want_md5: bytes):
        self.inner = inner
        self._md5 = hashlib.md5()
        self._want = want_md5
        self._checked = False

    def read(self, n: int = -1) -> bytes:
        data = self.inner.read(n)
        if data:
            self._md5.update(data)
        elif not self._checked:
            self._checked = True
            if self._md5.digest() != self._want:
                raise S3Error("BadDigest")
        return data




class S3Error(Exception):
    def __init__(self, code: str):
        super().__init__(code)
        self.api = s3err.get(code)


def _http_date(ns: int) -> str:
    return email.utils.formatdate(ns / 1e9, usegmt=True)


def _iso_date(ns: int) -> str:
    return datetime.datetime.fromtimestamp(
        ns / 1e9, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


def _xml(root: ET.Element) -> bytes:
    return (b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root))


def _parse_duration(s: str) -> float:
    """'10s' / '2m' / '500ms' -> seconds (cmd/config duration keys)."""
    from ..utils.kvconfig import parse_duration
    return parse_duration(s, 10.0)


class _DeadlineRFile:
    """Per-connection read deadline plumbing (cmd/http/server.go:185
    setCtx read deadlines rebuilt for a blocking rfile).

    Two regimes share one socket timeout: between requests and while
    parsing the request line/headers, a flat ``header_timeout`` applies
    (idle + slowloris-header cutoff).  While a handler reads a BODY the
    wrapper is armed with an ABSOLUTE deadline: every read re-arms the
    socket timeout to ``min(remaining, header_timeout)``, so a client
    trickling one byte per interval cannot extend its total budget —
    the per-recv timeout shrinks to whatever of the body deadline is
    left (the slow-body watchdog)."""

    def __init__(self, raw, sock, header_timeout: float):
        self._raw = raw
        self._sock = sock
        self._header_timeout = header_timeout
        self._deadline: float | None = None

    def arm(self, budget_s: float) -> None:
        self._deadline = time.monotonic() + budget_s

    def disarm(self) -> None:
        self._deadline = None
        try:
            self._sock.settimeout(self._header_timeout)
        except OSError:
            pass    # connection already torn down

    def _tick(self) -> None:
        if self._deadline is None:
            return
        remaining = self._deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError("request body deadline exceeded")
        try:
            self._sock.settimeout(min(remaining, self._header_timeout))
        except OSError:
            pass

    def read(self, n: int = -1) -> bytes:
        if self._deadline is None:
            return self._raw.read(n)
        # armed: one plain read(n) would loop on recv INSIDE the
        # buffered reader — a client trickling bytes under the per-recv
        # timeout would never surface the absolute deadline.  read1
        # issues at most one syscall, so every recv is preceded by a
        # deadline check and capped at the remaining budget.
        out = bytearray()
        want = n if n is not None and n >= 0 else None
        while want is None or len(out) < want:
            self._tick()
            chunk = self._raw.read1(
                65536 if want is None else want - len(out))
            if not chunk:
                break
            out += chunk
        return bytes(out)

    def readline(self, limit: int = -1) -> bytes:
        self._tick()
        return self._raw.readline(limit)

    def readinto(self, b) -> int:
        self._tick()
        return self._raw.readinto(b)

    def close(self) -> None:
        self._raw.close()

    @property
    def closed(self):
        return self._raw.closed

    def __getattr__(self, name):
        return getattr(self._raw, name)


def _try(fn):
    """Run a config parser, translating its ValueError into an S3Error
    (carrying the parser's .code when present)."""
    try:
        return fn()
    except ValueError as e:
        raise S3Error(getattr(e, "code", "MalformedXML")) from e


def _canned_acl_xml() -> bytes:
    """The fixed FULL_CONTROL owner ACL MinIO reports
    (cmd/bucket-handlers.go GetBucketACLHandler)."""
    root = ET.Element("AccessControlPolicy", xmlns=S3_NS)
    owner = ET.SubElement(root, "Owner")
    ET.SubElement(owner, "ID").text = "minio-tpu"
    acl = ET.SubElement(root, "AccessControlList")
    grant = ET.SubElement(acl, "Grant")
    grantee = ET.SubElement(
        grant, "Grantee",
        {"xmlns:xsi": "http://www.w3.org/2001/XMLSchema-instance",
         "xsi:type": "CanonicalUser"})
    ET.SubElement(grantee, "ID").text = "minio-tpu"
    ET.SubElement(grant, "Permission").text = "FULL_CONTROL"
    return _xml(root)


class S3Server:
    """Wires an ObjectLayer + credentials into an HTTP server."""

    def __init__(self, object_layer, access_key: str = "minioadmin",
                 secret_key: str = "minioadmin", region: str = "us-east-1",
                 host: str = "127.0.0.1", port: int = 0,
                 max_body_size: int = 1024 ** 3, iam=None, tls=None):
        self.layer = object_layer
        if iam is None:
            from ..iam.sys import IAMSys
            iam = IAMSys(object_layer, access_key, secret_key)
        self.iam = iam
        self.region = region
        self.max_body_size = max_body_size
        self.bucket_meta = BucketMetadataSys(object_layer)
        from ..utils.kvconfig import Config
        # config persists SEALED under the admin secret
        # (cmd/config-encrypted.go; secure/configcrypt.py) — plaintext
        # found on disk migrates at load, rotation re-seals in place
        self.config = Config(object_layer, secret=secret_key)
        # TLS front (secure/certs.py): an explicit CertManager wins;
        # otherwise the ``tls`` kvconfig subsystem (certs_dir layout)
        # arms it at boot.  Cert ROTATION is live via the manager's
        # mtime watcher; the handshake completes per connection in the
        # handler thread (never the accept loop).
        if tls is None:
            from ..secure.certs import CertManager
            tls = CertManager.from_config(self.config)
        self.tls = tls
        if tls is not None:
            # scheme-aware clients (S3Client/AdminClient on https
            # endpoints, the soak scrape) resolve the CA pin through
            # the process-global registry
            from ..secure import transport as _tls_transport
            _tls_transport.configure(tls)
        # etcd coordination backend (cmd/etcd.go): when configured, IAM
        # persists to etcd (cmd/iam-etcd-store.go) and federation DNS
        # records use the CoreDNS/skydns layout
        from ..utils import etcd as etcd_mod
        self.etcd = etcd_mod.from_config(self.config)
        if self.etcd is not None:
            self.iam.attach_etcd(self.etcd,
                                 self.config.get("etcd", "path_prefix"))
        from ..events import NotificationSys
        self.events = NotificationSys(self.bucket_meta, region=region)
        # wired in by server_main / tests when those subsystems are enabled
        self.replication = None  # ReplicationSys (minio_tpu/background)
        # quota's usage view (background/crawler.py UsageCache): the
        # last persisted crawler snapshot + a lock-cheap in-flight byte
        # delta.  Always attached, so hard bucket quotas enforce with
        # or without a running crawler (the cache lazily re-reads the
        # persisted usage.json when no cycle refreshes it).
        from ..background.crawler import UsageCache
        self.usage = UsageCache(object_layer)
        self.healer = None       # BackgroundHealer sweep
        self.crawler = None      # Crawler (scanner plane)
        self.mrf = None          # MRFQueue
        self.tracker = None      # DataUpdateTracker (crawler bloom filter)
        from ..crypto.kms import kms_from_env
        self.kms = kms_from_env(object_layer)
        from ..iam.openid import OpenIDProvider
        self.openid = OpenIDProvider.from_config(self.config)
        from ..iam.ldap import LDAPConfig, LDAPIdentity
        _lcfg = LDAPConfig.from_config(self.config)
        self.ldap = LDAPIdentity(_lcfg) if _lcfg.enabled else None
        # ILM tiering (cmd/bucket-lifecycle.go transitionObject): tier
        # registry persisted in the system volume
        from ..objectlayer.tiering import TransitionSys
        from ..storage.xl_storage import SYS_DIR
        blobs, _ = object_layer._fanout(
            lambda d: d.read_all(SYS_DIR, "tiers/tiers.json"))
        blob = next((b for b in blobs if b), None)
        self.transition = TransitionSys.from_json(object_layer, blob) \
            if blob else TransitionSys(object_layer)
        # observability (cmd/http-tracer.go, cmd/logger/audit.go):
        # trace hub is process-global (mirrors globalHTTPTrace); audit
        # log is per-server so deployments keep entries separate
        from ..obs import audit as _obs_audit
        from ..obs import lastminute as _obs_lastminute
        from ..obs import logger as _obs_logger
        from ..obs import trace as _obs_trace
        self.trace_hub = _obs_trace.HTTP_TRACE
        self.audit = _obs_audit.AuditLog()
        self.logger = _obs_logger.GLOBAL
        self.node_name = f"{host}:{port}"
        # last-minute per-API stats (cmd/last-minute.go role): feeds the
        # mt_s3_api_last_minute_* scrape families and the admin `top`
        # endpoint (hottest APIs)
        self.api_stats = _obs_lastminute.OpWindows(self.node_name)
        # telemetry egress plane (obs/egress.py): every config-driven
        # delivery target — logger/audit webhooks, the notify webhook,
        # broker targets — lives in this registry so the scrape, the
        # admin `targets` routes, and shutdown all see the same set
        from ..obs.egress import EgressRegistry
        self.egress = EgressRegistry()
        self._egress_owned = []
        # serializes reloads: two concurrent admin SetConfigKV calls
        # must not both tear down / rebuild the same target set
        # (duplicate registrations would leak unreachable senders)
        self._egress_reload_mu = threading.Lock()
        self.reload_egress_config()
        if self.config.get("compression", "enable") == "on":
            # build/load the native codec BEFORE serving so the first
            # request never blocks on a compile, and say which engine runs
            from .. import compress as mtc
            import logging
            if not mtc.native_available():
                logging.getLogger("minio_tpu").warning(
                    "native snappy codec unavailable; using the pure-"
                    "Python fallback (slow)")
        # live connections, so stop() can sever parked keep-alive
        # handlers instead of leaving zombie threads serving a
        # "stopped" server; _active_conns is the subset currently
        # INSIDE a request — the graceful drain lets those finish
        # while idle keep-alive parkers are severed immediately
        self._conns: set = set()
        self._active_conns: set = set()
        self._conns_mu = threading.Lock()
        # soak-plane status (minio_tpu/soak/report.py SoakStatus):
        # attached by a running soak conductor, read by admin soak-status
        self.soak = None
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        # severed keep-alives (shutdown drain, chaos) raise transport
        # errors in handler threads; drop them instead of printing a
        # traceback per connection
        from ..parallel.rpc import _quiet_connection_errors
        self.httpd.handle_error = _quiet_connection_errors(
            self.httpd.handle_error)
        if self.tls is not None:
            from ..secure.certs import enable_server_tls
            enable_server_tls(self.httpd, self.tls, "s3")
        self.port = self.httpd.server_address[1]
        # span attribution names the BOUND port (ephemeral binds resolve
        # only now); run_node overrides both with the cluster node_id
        self.node_name = f"{host}:{self.port}"
        self.api_stats.label = self.node_name
        _obs_trace.set_node_name(self.node_name)
        # federation binds the *actual* port (ephemeral binds resolve
        # only once the listener exists)
        from ..utils.fed_dns import FederationSys
        self.federation = FederationSys.from_config(
            self.config, host or "127.0.0.1", self.port)
        self._thread: threading.Thread | None = None
        # set by admin service?action=stop so a node-mode main thread
        # parked on it can finish shutdown (RPC plane + process exit)
        self.shutdown = threading.Event()
        # peer control-plane notifier (cluster mode; parallel/peer.py)
        self.peers = None
        # request admission throttle (cmd/handler-api.go:29-40
        # requestsPool/requestsDeadline; config keys cmd/config/api):
        # bounds concurrent S3 requests; excess waits up to the deadline
        # then gets 503 SlowDown instead of piling up threads
        self._req_waiters = 0
        self._req_waiters_mu = threading.Lock()
        self._req_max = 0
        self.reload_api_config()
        # apply persisted ``pipeline`` knobs to the layer (it booted
        # with env/defaults before this server's config existed)
        self.reload_pipeline_config()
        # push ``rpc`` streaming knobs into the shared internode plane
        self.reload_rpc_config()
        # push ``codec`` batching knobs into the shared batcher
        self.reload_codec_config()
        # push ``commit`` group-commit knobs into the shared commit
        # plane (group window, packing threshold)
        self.reload_commit_config()
        # push ``cache`` hot-read knobs into every leaf layer's plane
        # and wire the admission heat source to this server's
        # last-minute API stats
        self.reload_cache_config()
        # push ``heal``/``scanner`` pacing into attached background
        # planes (they may also attach later via attach_background)
        self.reload_background_config()
        # arm the external policy webhook (``policy_opa``) on the IAM
        # plane when configured
        self.reload_policy_config()
        # request X-ray + flight recorder (obs/flightrec.py): always-on
        # bounded rings of recent requests/errors/system snapshots,
        # queried by the admin ``xray`` route and dumped into forensic
        # bundles.  Per-server (like the audit log) so embedded
        # multi-node tests keep nodes apart.
        from ..obs.flightrec import FlightRecorder
        self.flightrec = FlightRecorder()
        # forensic trigger engine (obs/forensic.py): breach-shaped
        # signals snapshot the rings into a bounded bundle dir under
        # the first local drive (``forensic`` kvconfig subsystem);
        # None when disabled or no local drive exists (gateway modes)
        self.forensic = None
        self.reload_forensic_config()
        # SLO watchdog plane (obs/watchdog.py): the telemetry-history
        # sampler + burn-rate/drift rule engine over it (``watchdog``
        # kvconfig subsystem); None when disabled — the idle contract
        # means no mt-obs-history thread and no mt_alert_*/mt_history_*
        # family in the scrape
        self.watchdog = None
        self.reload_watchdog_config()
        # workload attribution plane (obs/metering.py): bounded
        # per-(bucket, api, access-key) registry + heavy-hitter
        # sketches (``metering`` kvconfig subsystem); None when
        # disabled — the idle contract means no charge branch at
        # completion-record time and no mt_bucket_*/mt_tenant_*
        # family in the scrape
        self.metering = None
        self.reload_metering_config()

    def reload_api_config(self) -> None:
        """(Re)derive the request-plane knobs from the ``api`` kvconfig
        subsystem — called at boot and after admin SetConfigKV so an
        operator can retune deadlines/limits on a live server."""
        try:
            req_max = int(self.config.get("api", "requests_max") or 0)
        except ValueError:
            req_max = 0
        if req_max <= 0:
            req_max = 16 * (os.cpu_count() or 8)   # auto sizing
        self.requests_deadline_s = _parse_duration(
            self.config.get("api", "requests_deadline") or "10s")
        if req_max != self._req_max:
            # swap, never resize: in-flight requests release to the
            # semaphore they acquired (dispatch captures the object)
            self._req_max = req_max
            self._req_sem = threading.BoundedSemaphore(req_max)
        # load shedding (cmd/handler-api.go maxClients 503 path): bound
        # the WAITING line too — when the queue is full a request is
        # shed immediately with 503 + Retry-After instead of parking
        # one more worker thread behind the semaphore
        try:
            req_queue = int(self.config.get("api", "requests_queue")
                            or 0)
        except ValueError:
            req_queue = 0
        self.requests_queue_max = req_queue if req_queue > 0 \
            else 2 * req_max
        # per-connection deadlines (cmd/http/server.go:185): header/idle
        # socket timeout + slow-body budget per request (scaled by the
        # declared size over the floor rate, so a large upload making
        # progress is never cut while a trickler cannot stall forever)
        self.read_header_timeout_s = _parse_duration(
            self.config.get("api", "read_header_timeout") or "30s")
        self.body_deadline_s = _parse_duration(
            self.config.get("api", "body_deadline") or "2m")
        try:
            self.body_min_rate_bps = int(
                self.config.get("api", "body_min_rate") or 0)
        except ValueError:
            self.body_min_rate_bps = 1 << 20
        # graceful shutdown drain: how long stop() lets in-flight
        # requests finish (after refusing new connections) before
        # severing; 0 = sever immediately (the PR-1 behavior)
        self.shutdown_drain_s = _parse_duration(
            self.config.get("api", "shutdown_drain_s") or "5s")
        # node memory governor (utils/memgov.py): watermark + retry
        # hint, and the Select scanner block size — all live-reloadable
        from ..utils import memgov as _memgov
        _memgov.GOVERNOR.load(self.config)
        try:
            self.select_block_bytes = max(
                64 * 1024,
                int(self.config.get("api", "select_block_bytes")
                    or 1 << 20))
        except ValueError:
            self.select_block_bytes = 1 << 20

    def reload_pipeline_config(self) -> None:
        """Push the ``pipeline`` kvconfig knobs (PUT pipeline depth,
        per-drive writer queue depth) into every leaf erasure layer —
        at boot and after admin SetConfigKV, so the live data plane
        retunes without a restart."""
        from ..objectlayer.metacache import leaf_layers_of
        for leaf in leaf_layers_of(self.layer):
            reload = getattr(leaf, "reload_pipeline_config", None)
            if reload is not None:
                try:
                    reload(self.config)
                except Exception:  # noqa: BLE001 — bad knob value must
                    pass           # not take the server down

    def reload_rpc_config(self) -> None:
        """Push the ``rpc`` streaming knobs (stream_enable,
        stream_chunk_bytes) into the process-wide internode streaming
        config — at boot and after admin SetConfigKV, so chunked shard
        streaming retunes on a live cluster (a fresh kvconfig.Config
        cannot see this server's dynamic layer)."""
        from ..parallel import rpc as _rpc
        try:
            _rpc.STREAM.load(self.config)
        except Exception:  # noqa: BLE001 — bad knob must not kill boot
            pass

    def reload_codec_config(self) -> None:
        """Push the ``codec`` batching knobs (enable, batch_window_us,
        max_batch_blocks, queue_depth) into the process-wide
        cross-request codec batcher — at boot and after admin
        SetConfigKV, so the combining window retunes on a live server
        (a fresh kvconfig.Config cannot see this server's dynamic
        layer)."""
        from ..parallel import batcher as _batcher
        try:
            _batcher.CONFIG.load(self.config)
        except Exception:  # noqa: BLE001 — bad knob must not kill boot
            pass

    def reload_commit_config(self) -> None:
        """Push the ``commit`` group-commit knobs (enable,
        group_window_us, max_batch, pack_threshold, segment_max_bytes)
        into the process-wide commit-plane config — at boot and after
        admin SetConfigKV, so the group window and packing threshold
        retune on a live server (a fresh kvconfig.Config cannot see
        this server's dynamic layer)."""
        from ..storage import commit as _commit
        try:
            _commit.CONFIG.load(self.config)
        except Exception:  # noqa: BLE001 — bad knob must not kill boot
            pass

    def reload_cache_config(self) -> None:
        """Push the ``cache`` kvconfig knobs (enable, max_bytes,
        heat_threshold, singleflight_queue, window_bytes) into the
        process-wide hot-read config and wire each leaf layer's plane
        to THIS server's last-minute per-API stats as its admission
        heat source — at boot and after admin SetConfigKV, so the
        hot-object cache retunes on a live server.  Disabling releases
        every cached byte back to the memory governor immediately."""
        from ..objectlayer import hotread as _hotread
        try:
            _hotread.CONFIG.load(self.config)
        except Exception:  # noqa: BLE001 — bad knob must not kill boot
            pass
        stats = self.api_stats

        def _get_heat() -> int:
            w = stats.windows.get("GetObject")
            return w.total()[0] if w is not None else 0

        # per-key admission heat: when the metering plane is armed its
        # count-min estimate gates admission per OBJECT; otherwise the
        # plane falls back to the global GetObject rate above
        metering = getattr(self, "metering", None)
        heat_key = metering.key_heat if metering is not None else None

        from ..objectlayer.metacache import leaf_layers_of
        for leaf in leaf_layers_of(self.layer):
            plane = getattr(leaf, "hotread", None)
            if plane is not None:
                plane.heat_fn = _get_heat
                plane.heat_key_fn = heat_key
                if not _hotread.CONFIG.enable:
                    plane.clear()

    def reload_policy_config(self) -> None:
        """(Re)build the external policy webhook from the
        ``policy_opa`` kvconfig subsystem and swap it under
        ``IAMSys.is_allowed`` — at boot and after admin SetConfigKV,
        so an operator can point the cluster at (or away from) an OPA
        endpoint on a live server.  An empty url restores local policy
        evaluation."""
        from ..secure.opa import OpaWebhook
        try:
            self.iam.authorizer = OpaWebhook.from_config(self.config)
        except Exception:  # noqa: BLE001 — a bad knob value must not
            pass           # take the server (or the IAM plane) down

    def reload_forensic_config(self) -> None:
        """(Re)build the forensic trigger engine from the ``forensic``
        kvconfig subsystem — at boot and after admin SetConfigKV, so
        an operator can retune thresholds/cooldowns (or disable the
        engine) on a live server.  Trigger cooldown history resets on
        reload; the bundle dir is reaped by whichever engine writes
        next."""
        from ..obs.forensic import ForensicSys
        old = getattr(self, "forensic", None)
        if old is not None:
            # the outgoing engine's in-flight bundle write finishes
            # (bounded) before the swap — a dangling mt-forensic-dump
            # thread must not write/reap the dir after a reload
            old.join(timeout=5.0)
        try:
            self.forensic = ForensicSys.from_server(self)
        except Exception:  # noqa: BLE001 — a bad knob value must not
            self.forensic = None       # take the server down

    def reload_watchdog_config(self) -> None:
        """(Re)build the SLO watchdog from the ``watchdog`` kvconfig
        subsystem — at boot and after admin SetConfigKV.  A reload
        replaces the engine wholesale: history rings reset (documented
        in the subsystem comment) and alert state starts clean."""
        from ..obs.watchdog import WatchdogSys
        old = getattr(self, "watchdog", None)
        if old is not None:
            # stop the outgoing sampler thread before the swap — two
            # mt-obs-history threads must never tick concurrently
            old.stop(timeout=5.0)
        try:
            self.watchdog = WatchdogSys.from_server(self)
        except Exception:  # noqa: BLE001 — a bad knob value must not
            self.watchdog = None       # take the server down
        if self.watchdog is not None:
            self.watchdog.start()

    def reload_metering_config(self) -> None:
        """(Re)build the workload attribution plane from the
        ``metering`` kvconfig subsystem — at boot and after admin
        SetConfigKV.  A reload replaces the registry wholesale
        (counters and sketches reset, documented in the subsystem
        comment), then re-runs the cache reload so every hot-read
        plane's per-key heat source follows the swap."""
        from ..obs.metering import Metering
        try:
            self.metering = Metering.from_server(self)
        except Exception:  # noqa: BLE001 — a bad knob value must not
            self.metering = None       # take the server down
        self.reload_cache_config()

    def reload_background_config(self) -> None:
        """Push the ``heal``/``scanner`` pacing knobs into every
        attached background plane (attach_background) — at boot and
        after admin SetConfigKV, so heal/scan IO yielding retunes on a
        live server.  Duck-typed on the pacing attributes: a healer
        exposes ``pace_s``/``deep_every``, a crawler
        ``delay_mult``/``max_wait_s``."""
        cfg = self.config
        try:
            bitrot = cfg.get("heal", "bitrotscan") == "on"
            pace = _parse_duration(cfg.get("heal", "max_sleep") or "1s")
            delay = float(cfg.get("scanner", "delay") or 0)
            max_wait = _parse_duration(
                cfg.get("scanner", "max_wait") or "15s")
            rb_enable = cfg.get("rebalance", "enable") == "on"
            rb_workers = int(cfg.get("rebalance", "max_workers") or 1)
            rb_bw = int(cfg.get("rebalance", "bandwidth") or 0)
        except (KeyError, ValueError):
            return
        for svc in getattr(self, "_background", []):
            if hasattr(svc, "bandwidth_bps"):
                # the rebalancer: its own enable/workers/bandwidth knobs
                # plus the healer's IO self-pacing cap
                svc.enabled = rb_enable
                svc.max_workers = rb_workers
                svc.bandwidth_bps = rb_bw
                svc.pace_s = pace
                svc.monitor.set_limit("rebalance", rb_bw)
                continue
            if hasattr(svc, "pace_s"):
                svc.pace_s = pace
                # bitrotscan=on forces deep sweeps; turning it back
                # off must RESTORE the constructed cadence (the
                # override is remembered so a live off actually lands)
                if bitrot and not hasattr(svc, "_bitrot_prev"):
                    svc._bitrot_prev = svc.deep_every
                    svc.deep_every = 1       # deep-scan EVERY sweep
                elif not bitrot and hasattr(svc, "_bitrot_prev"):
                    svc.deep_every = svc._bitrot_prev
                    del svc._bitrot_prev
            if hasattr(svc, "delay_mult"):
                svc.delay_mult = delay
                svc.max_wait_s = max_wait

    def reload_egress_config(self) -> None:
        """(Re)build every config-driven egress target from the
        ``logger_webhook`` / ``audit_webhook`` / ``notify_*`` kvconfig
        subsystems — called at boot and after admin SetConfigKV so an
        operator can repoint endpoints or retune queue knobs on a live
        server.  Replaced targets are closed (their queued records
        spill to their disk stores).  One bad subsystem config must not
        take the others' telemetry down: each target builds under its
        own guard, and a failure is logged and skipped."""
        with self._egress_reload_mu:
            self._reload_egress_locked()

    def _reload_egress_locked(self) -> None:
        from ..events import WebhookTarget
        from ..events.brokers import BROKER_KINDS, target_from_config
        from ..obs import logger as _obs_logger
        from ..obs.egress import config_queue_limit
        for t in getattr(self, "_egress_owned", []):
            try:
                if t in self.logger.targets:
                    self.logger.targets.remove(t)
                if t in self.audit.targets:
                    self.audit.targets.remove(t)
                if getattr(t, "arn", ""):
                    self.events.remove_target(t.arn)
                self.egress.remove(t)
                t.close()
            except Exception:  # noqa: BLE001 — a broken old target
                pass           # must not block the reload
        self._egress_owned = []
        cfg = self.config

        def _own(t):
            self.egress.register(t)
            self._egress_owned.append(t)
            return t

        for sub, sink in (("logger_webhook", self.logger.targets),
                          ("audit_webhook", self.audit.targets),
                          ("alert_webhook", None)):
            try:
                if cfg.get(sub, "enable") != "on":
                    continue
                size = config_queue_limit(cfg, sub, "queue_size")
                t = _own(_obs_logger.HTTPLogTarget(
                    cfg.get(sub, "endpoint"), cfg.get(sub, "auth_token"),
                    target_type=sub.split("_", 1)[0],
                    queue_limit=size, store_limit=size,
                    store_dir=cfg.get(sub, "queue_dir") or None))
                if sink is not None:
                    sink.append(t)
                # alert targets have no log sink: the watchdog engine
                # pushes alert events into them directly (it discovers
                # them in the egress registry by target_type)
            except Exception as e:  # noqa: BLE001 — bad subsystem config
                self.logger.error(f"egress: building {sub} target "
                                  f"failed: {e}")
        try:
            if cfg.get("notify_webhook", "enable") == "on":
                # config-driven target registration (cmd/config/notify):
                # the ARN a PUT-notification config may reference
                lim = config_queue_limit(cfg, "notify_webhook",
                                         "queue_limit")
                self.events.register_target(_own(WebhookTarget(
                    "arn:minio:sqs::1:webhook",
                    cfg.get("notify_webhook", "endpoint"),
                    auth_token=cfg.get("notify_webhook", "auth_token"),
                    store_dir=cfg.get("notify_webhook", "queue_dir")
                    or None,
                    queue_limit=lim, store_limit=lim)))
        except Exception as e:  # noqa: BLE001 — bad subsystem config
            self.logger.error(f"egress: building notify_webhook target "
                              f"failed: {e}")
        for kind in BROKER_KINDS:
            try:
                t = target_from_config(kind, cfg)
            except Exception as e:  # noqa: BLE001 — bad subsystem config
                self.logger.error(f"egress: building notify_{kind} "
                                  f"target failed: {e}")
                continue
            if t is not None:
                self.events.register_target(_own(t))

    def body_budget_s(self, content_length: int) -> float:
        """Read budget for one request body: the flat deadline plus
        declared-size / floor-rate headroom."""
        budget = self.body_deadline_s
        if content_length > 0 and self.body_min_rate_bps > 0:
            budget += content_length / self.body_min_rate_bps
        return budget

    def attach_tracker(self, tracker) -> None:
        """Wire the data-update tracker into event marking AND listing-
        cache validity (the metacache consults it instead of waiting
        out its TTL — cmd/metacache-bucket.go coupling)."""
        self.tracker = tracker
        from ..objectlayer.metacache import managers_of
        for mc in managers_of(self.layer):
            mc.tracker = tracker

    def attach_peers(self, notifier) -> None:
        """Wire the peer fan-out: IAM/bucket-metadata mutations reload on
        every node immediately (cmd/peer-rest-common.go:27-61), and the
        trace hub keeps a pollable ring for cross-node aggregation."""
        self.peers = notifier
        self.bucket_meta.on_change = notifier.bucket_meta_changed
        self.iam.on_change = notifier.iam_changed
        self.trace_hub.enable_ring()

    def attach_background(self, *services) -> None:
        """Register background loops (crawler, healer) whose lifecycle
        follows the server's: started on start(), stopped on stop()
        (initDataCrawler / initBackgroundHealing, cmd/server-main.go)."""
        self._background = getattr(self, "_background", [])
        self._background.extend(services)
        for svc in services:
            # a crawler refreshes this server's quota usage view at
            # the end of every cycle (duck-typed on the attribute so
            # test fakes without it still attach)
            if hasattr(svc, "usage_cache"):
                svc.usage_cache = self.usage
        # late attachments pick up the ``heal``/``scanner`` pacing
        # knobs the boot-time reload could not reach
        self.reload_background_config()

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True,
                                        name="mt-s3-server")
        self._thread.start()
        for svc in getattr(self, "_background", []):
            svc.start()

    def stop(self) -> None:
        self._stopping = True          # health probes report offline
        for svc in getattr(self, "_background", []):
            try:
                svc.stop()
            except Exception:  # noqa: BLE001 — shutdown must proceed
                pass
        self.httpd.shutdown()
        # graceful drain (cmd/http/server.go Shutdown analog): the
        # listener closes FIRST, so new connections are refused while
        # in-flight requests get the drain budget to finish.  Idle
        # keep-alive handlers have no request in flight — severed
        # immediately; handlers finishing a request during the drain
        # close their connection themselves (_stopping gate).
        self.httpd.server_close()
        from ..parallel.rpc import sever_connections
        drain_s = getattr(self, "shutdown_drain_s", 0.0)
        if drain_s > 0:
            with self._conns_mu:
                idle = [c for c in self._conns
                        if c not in self._active_conns]
            sever_connections(idle)
            deadline = time.monotonic() + drain_s
            while time.monotonic() < deadline:
                with self._conns_mu:
                    if not self._active_conns:
                        break
                time.sleep(0.02)
        # whatever is still parked or past the drain budget dies now
        with self._conns_mu:
            conns = list(self._conns)
        sever_connections(conns)
        # watchdog down BEFORE the egress plane: the sampler thread
        # (mt-obs-history) joins so no alert event is pushed into a
        # target that is mid-close below
        if getattr(self, "watchdog", None) is not None:
            self.watchdog.stop(timeout=5.0)
        self.events.close()
        # egress plane down WITH the server: sender threads join, queued
        # records spill to their disk stores, and this server's targets
        # leave the process-global logger so a later server (or test)
        # never delivers through a dead target
        for t in getattr(self, "_egress_owned", []):
            if t in self.logger.targets:
                self.logger.targets.remove(t)
        if getattr(self, "egress", None) is not None:
            self.egress.close_all()
        # writer plane down WITH the server: per-drive writer threads
        # join, queued ops fail with PlaneClosed (in-flight PUTs abort
        # and clean their tmp files), blocked enqueuers wake.  The
        # plane reopens lazily if a shared layer serves again later.
        from ..storage.writers import close_write_planes
        close_write_planes(self.layer)
        # disk-cache layers down WITH the server: writeback + GC
        # threads (mt-diskcache-*) join so nothing outlives stop()
        from ..objectlayer.diskcache import CacheObjects
        lay, seen = self.layer, set()
        while lay is not None and id(lay) not in seen:
            seen.add(id(lay))
            if isinstance(lay, CacheObjects):
                lay.close()
            lay = lay.__dict__.get("inner")
        # hot-read plane: release every cached byte back to the memory
        # governor — a stopped node holds no resident hot tier, and the
        # process-wide inuse accounting must read zero at idle
        from ..objectlayer.metacache import leaf_layers_of
        for leaf in leaf_layers_of(self.layer):
            plane = getattr(leaf, "hotread", None)
            if plane is not None:
                plane.clear()
        # an in-flight forensic bundle write finishes (bounded) so the
        # thread-hygiene assertions never see a dangling dump worker
        if getattr(self, "forensic", None) is not None:
            self.forensic.join(timeout=10.0)
        if self.peers is not None:
            self.peers.close()

    @property
    def endpoint(self) -> str:
        scheme = "https" if self.tls is not None else "http"
        return f"{scheme}://127.0.0.1:{self.port}"

    def notify(self, event_name: str, bucket: str, oi,
               req_params: dict | None = None) -> None:
        """Fire a bucket event into the notification system."""
        if self.tracker is not None and oi is not None:
            # feed the crawler's change bloom filter on every mutation
            self.tracker.mark(bucket, getattr(oi, "name", ""))
        if self.peers is not None and oi is not None:
            # feed every PEER's tracker too: their cached listings for
            # this bucket go stale now, not after the metacache TTL
            self.peers.object_changed(bucket, getattr(oi, "name", ""))
        self.events.send(event_name, bucket, oi, req_params or {})

    def replicate(self, bucket: str, oi, delete: bool = False) -> None:
        """Queue async replication if the bucket's config asks for it
        (no-op until ReplicationSys is attached)."""
        if self.replication is not None:
            self.replication.queue(bucket, oi, delete=delete)


def _layer_set_drive_count(layer) -> int:
    """Drives per erasure set for any topology shape (storage-class
    parity is bounded by the SET size, not total drives)."""
    n = getattr(layer, "set_drive_count", 0)
    if n:
        return n
    pools = getattr(layer, "pools", None)
    if pools:
        return getattr(pools[0], "set_drive_count",  # mt-lint: ok(pool-routing) shape probe — every pool shares the set geometry, any index answers
                       0)
    return len(getattr(layer, "disks", []) or [])


def _api_name(method: str, bucket: str, key: str, q1: dict) -> str:
    """Best-effort S3 API name for traces/audit (the reference names come
    from mux route registration, cmd/api-router.go)."""
    if bucket == "minio-tpu" or not bucket:
        if method == "POST" and not bucket:
            return "STS"
        return "AdminAPI" if bucket else "ListBuckets"
    sub = {"uploads": "MultipartUpload", "uploadId": "MultipartUpload",
           "tagging": "Tagging", "retention": "Retention",
           "legal-hold": "LegalHold", "select": "SelectObjectContent",
           "versioning": "Versioning", "policy": "BucketPolicy",
           "lifecycle": "BucketLifecycle", "encryption": "BucketEncryption",
           "replication": "BucketReplication", "notification":
           "BucketNotification", "object-lock": "ObjectLockConfig",
           "versions": "ListObjectVersions", "delete": "DeleteObjects"}
    feature = next((v for k, v in sub.items() if k in q1), "")
    if key:
        base = {"GET": "GetObject", "HEAD": "HeadObject",
                "PUT": "PutObject", "DELETE": "DeleteObject",
                "POST": "PostObject"}.get(method, method)
        if feature and feature != "MultipartUpload":
            return {"GET": "Get", "PUT": "Put",
                    "DELETE": "Delete"}.get(method, "") + feature \
                if feature in ("Tagging", "Retention", "LegalHold") \
                else feature
        if feature == "MultipartUpload":
            return {"POST": "CompleteMultipartUpload"
                    if "uploadId" in q1 else "CreateMultipartUpload",
                    "PUT": "UploadPart", "GET": "ListParts",
                    "DELETE": "AbortMultipartUpload"}.get(method, base)
        return base
    base = {"GET": "ListObjectsV2" if q1.get("list-type") == "2"
            else "ListObjectsV1",
            "HEAD": "HeadBucket", "PUT": "MakeBucket",
            "DELETE": "DeleteBucket", "POST": "PostPolicyBucket"}
    if feature:
        return ({"GET": "Get", "PUT": "Put", "DELETE": "Delete"}
                .get(method, "") + feature) \
            if feature.startswith("Bucket") or feature == "Versioning" \
            else feature
    return base.get(method, method)


def _make_handler(srv: S3Server):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "MinioTPU"

        # -- plumbing ------------------------------------------------------

        def setup(self):
            # per-connection deadlines (cmd/http/server.go:185): the
            # socket timeout covers request-line/header reads and
            # keep-alive idle; the rfile wrapper adds the absolute
            # slow-body budget, armed per request in _dispatch.
            # (header SIZE is already bounded by http.server: 64 KiB
            # per line, 100 headers max)
            self.timeout = getattr(srv, "read_header_timeout_s", None)
            if srv.tls is not None:
                # deferred TLS handshake, in THIS handler thread under
                # the header deadline (the accept loop never blocks on
                # a slow client's handshake); failure counts into
                # mt_tls_handshake_failed_total and tears down just
                # this connection
                srv.tls.handshake(self.request, "s3",
                                  timeout=self.timeout or 30.0)
            super().setup()
            self.rfile = _DeadlineRFile(self.rfile, self.connection,
                                        self.timeout or 30.0)
            with srv._conns_mu:
                srv._conns.add(self.connection)

        def finish(self):
            try:
                super().finish()
            finally:
                with srv._conns_mu:
                    srv._conns.discard(self.connection)

        def log_message(self, fmt, *args):  # quiet; tracing hooks later
            pass

        def _split(self):
            u = urllib.parse.urlsplit(self.path)
            path = urllib.parse.unquote(u.path)
            query = urllib.parse.parse_qs(u.query, keep_blank_values=True)
            parts = path.lstrip("/").split("/", 1)
            bucket = parts[0]
            key = parts[1] if len(parts) > 1 else ""
            return path, bucket, key, query

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length") or 0)
            if n > srv.max_body_size:
                # reject before buffering: unauthenticated clients must not
                # be able to force huge allocations
                raise S3Error("EntityTooLarge")
            if not n:
                return b""
            from ..obs import stages as _stages
            with _stages.stage("body_read"):
                return self.rfile.read(n)

        def _auth(self, path, query, payload: bytes) -> bytes:
            from ..obs import stages as _stages
            self._query_token = query.get("X-Amz-Security-Token", [""])[0]
            with _stages.stage("auth"):
                out = self._auth_inner(path, query, payload)
                self._check_session_token()
            return out

        def _auth_inner(self, path, query, payload: bytes) -> bytes:
            """Authenticate; returns the effective payload (aws-chunked
            bodies are signature-verified per chunk and de-framed).  Sets
            self.access_key for authorization."""
            lookup = srv.iam.lookup_secret
            hdrs = {k: v for k, v in self.headers.items()}
            try:
                if "Authorization" not in hdrs and \
                        "X-Amz-Signature" not in query and \
                        not ("Signature" in query and
                             "AWSAccessKeyId" in query):
                    # anonymous request: authorization happens against the
                    # bucket policy alone (cmd/auth-handler.go authTypeAnonymous)
                    self.access_key = ""
                    sha = self.headers.get("x-amz-content-sha256")
                    if sha and sha != sigv4.UNSIGNED_PAYLOAD:
                        if hashlib.sha256(payload).hexdigest() != sha:
                            raise S3Error("BadDigest")
                    return payload
                auth_hdr = hdrs.get("Authorization", "")
                if auth_hdr.startswith("AWS "):
                    # Signature V2 header auth (cmd/signature-v2.go)
                    from . import sigv2
                    self.access_key = sigv2.verify_request(
                        lookup, self.command, path, query, hdrs)
                    return payload
                if "Signature" in query and "AWSAccessKeyId" in query:
                    # presigned V2
                    from . import sigv2
                    self.access_key = sigv2.verify_presigned(
                        lookup, self.command, path, query, hdrs)
                    return payload
                if "X-Amz-Signature" in query:
                    self.access_key = sigv4.verify_presigned(
                        lookup, self.command, path, query, hdrs,
                        region=srv.region)
                    return payload
                sha = self.headers.get("x-amz-content-sha256",
                                       sigv4.UNSIGNED_PAYLOAD)
                if sha == sigv4.STREAMING_PAYLOAD:
                    self.access_key, key, seed, amz_date, scope = \
                        sigv4.verify_request_streaming(
                            lookup, self.command, path, query, hdrs,
                            region=srv.region)
                    return sigv4.decode_chunked_payload(
                        payload, key, seed, amz_date, scope)
                if sha != sigv4.UNSIGNED_PAYLOAD:
                    got = hashlib.sha256(payload).hexdigest()
                    if got != sha:
                        raise S3Error("BadDigest")
                self.access_key = sigv4.verify_request(
                    lookup, self.command, path, query, hdrs, sha,
                    region=srv.region)
                return payload
            except sigv4.SigV4Error as e:
                raise S3Error(e.code) from e

        def _allow(self, action: str, resource: str = "") -> None:
            """Authorize the authenticated key for an S3 action: bucket
            policy first (explicit Deny wins, Allow grants even anonymous),
            then IAM (checkRequestAuthType -> IAMSys.IsAllowed)."""
            from ..obs import stages as _stages
            with _stages.stage("policy"):
                self._allow_inner(action, resource)

        def _allow_inner(self, action: str, resource: str = "") -> None:
            bucket = resource.split("/", 1)[0]
            # bucket policy can only speak for s3: actions — admin:* must
            # never be grantable by a bucket document
            if bucket and action.startswith("s3:"):
                try:
                    pol = srv.bucket_meta.get_bucket_policy(bucket)
                    verdict = pol.is_allowed(
                        self.access_key, action, resource) \
                        if pol is not None else None
                except Exception as e:  # noqa: BLE001 — fail CLOSED: an
                    # unevaluable policy must not silently drop its Denies
                    raise S3Error("AccessDenied") from e
                if verdict is False:
                    raise S3Error("AccessDenied")
                if verdict is True:
                    # a bucket-policy Allow still intersects with an STS
                    # session policy — temp creds never exceed their bound
                    if srv.iam.session_policy_allows(self.access_key,
                                                     action, resource):
                        return
                    raise S3Error("AccessDenied")
            if not self.access_key or \
                    not srv.iam.is_allowed(self.access_key, action,
                                           resource):
                raise S3Error("AccessDenied")

        def _send_prologue(self, status: int, sent_bytes: int,
                           entity_len: int, content_type: str,
                           headers: dict | None):
            """Shared response plumbing (metrics, trace bookkeeping,
            status line + common headers) for _send and _send_stream.
            sent_bytes feeds metrics (0 for HEAD); entity_len is the
            Content-Length header value."""
            from ..admin.metrics import GLOBAL as mtr
            mtr.inc("mt_s3_requests_total",
                    {"method": self.command, "status": str(status)})
            mtr.inc("mt_s3_tx_bytes_total", value=sent_bytes)
            self._resp_status = status
            self._resp_headers = dict(headers or {})
            self._resp_bytes = getattr(self, "_resp_bytes", 0) + sent_bytes
            if not getattr(self, "_ttfb_ns", 0) and \
                    getattr(self, "_t0_ns", 0):
                import time as _time
                self._ttfb_ns = _time.time_ns() - self._t0_ns
            self.send_response(status)
            self.send_header("x-amz-request-id",
                             getattr(self, "_req_id", None)
                             or uuid.uuid4().hex[:16])
            self.send_header("Server", "MinioTPU")
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(entity_len))
            self.end_headers()

        def _send(self, status: int, body: bytes = b"",
                  content_type: str = "application/xml",
                  headers: dict | None = None,
                  content_length: int | None = None):
            """content_length: explicit value for HEAD responses (body is
            not sent but the header must describe the entity)."""
            self._send_prologue(
                status, len(body),
                len(body) if content_length is None else content_length,
                content_type, headers)
            if body and self.command != "HEAD":
                from ..obs import stages as _stages
                with _stages.stage("body_write"):
                    self.wfile.write(body)

        def _send_stream(self, status: int, gen, total: int,
                         content_type: str, headers: dict | None = None):
            """Stream a known-length body chunk by chunk (the
            NewGetObjectReader pipeline end, cmd/object-api-utils.go:586).
            On a mid-stream failure the connection is dropped — the
            short body vs Content-Length signals truncation."""
            # pull the first chunk BEFORE committing the status line so
            # an immediately-failing read still yields a proper XML error
            it = iter(gen)
            first = b""
            if self.command != "HEAD" and total:
                try:
                    first = next(it)
                except StopIteration:
                    first = b""
            self._send_prologue(status, total, total, content_type,
                                headers)
            from ..obs import stages as _stages
            try:
                if first:
                    with _stages.stage("body_write"):
                        self.wfile.write(first)
                # pull OUTSIDE the body_write stage: producing a chunk
                # is drive_read/decode (attributed inside the
                # generator), not socket time
                while True:
                    try:
                        chunk = next(it)
                    except StopIteration:
                        break
                    if chunk:
                        with _stages.stage("body_write"):
                            self.wfile.write(chunk)
            except (ConnectionError, TimeoutError):
                # the client died mid-body: propagate so the dispatch
                # abort catch stamps the completion record with the
                # ``aborted`` marker and the stage vector it already
                # accumulated (tests/test_chaos_network.py reset drill)
                self.close_connection = True
                raise
            except Exception:   # noqa: BLE001 — headers are gone; a
                # second response would corrupt the stream
                self.close_connection = True

        def _send_chunked(self, status: int, chunks, content_type: str,
                          headers: dict | None = None,
                          head: bytes = b""):
            """Stream an UNKNOWN-length body via chunked transfer
            encoding (SelectObjectContent event streams — the response
            length is only known once the scan finishes, and buffering
            it would defeat the O(block) scanner).  ``head`` is written
            first (frames accumulated before the caller decided to
            stream).  A mid-stream failure drops the connection: the
            missing terminal 0-chunk signals truncation to the client,
            the chunked-framing analog of the short-body signal in
            _send_stream."""
            from ..admin.metrics import GLOBAL as mtr
            mtr.inc("mt_s3_requests_total",
                    {"method": self.command, "status": str(status)})
            self._resp_status = status
            self._resp_headers = dict(headers or {})
            if not getattr(self, "_ttfb_ns", 0) and \
                    getattr(self, "_t0_ns", 0):
                import time as _time
                self._ttfb_ns = _time.time_ns() - self._t0_ns
            self.send_response(status)
            self.send_header("x-amz-request-id",
                             getattr(self, "_req_id", None)
                             or uuid.uuid4().hex[:16])
            self.send_header("Server", "MinioTPU")
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Type", content_type)
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def write_chunk(data: bytes):
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data + b"\r\n")

            sent = 0
            try:
                if head:
                    write_chunk(head)
                    sent += len(head)
                for chunk in chunks:
                    if chunk:
                        write_chunk(chunk)
                        sent += len(chunk)
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except (ConnectionError, TimeoutError):
                # client death mid-stream: same abort contract as
                # _send_stream — the dispatch catch records the marker
                self.close_connection = True
                raise
            except Exception:   # noqa: BLE001 — headers are gone; drop
                self.close_connection = True
            finally:
                mtr.inc("mt_s3_tx_bytes_total", value=sent)
                self._resp_bytes = getattr(self, "_resp_bytes", 0) + sent

        def _fail(self, e: Exception, resource: str = ""):
            from ..crypto.sse import SSEError
            from ..parallel.dsync import LockLost, LockTimeout
            from ..utils.memgov import MemoryPressure
            if isinstance(e, MemoryPressure):
                # governor shed: same 503 + Retry-After contract as the
                # request-pool load-shed path — clients back off and
                # retry instead of watching the node OOM
                api = s3err.get("SlowDown")
                return self._send(
                    api.http_status,
                    s3err.to_xml(api, resource,
                                 getattr(self, "_req_id", "") or ""),
                    headers={"Retry-After":
                             str(max(1, int(e.retry_after_s)))})
            if isinstance(e, S3Error):
                api = e.api
            elif isinstance(e, (SSEError, sigv4.SigV4Error)):
                api = s3err.get(e.code)
            elif isinstance(e, ol.ObjectLayerError):
                api = s3err.from_object_error(e)
            elif isinstance(e, (LockTimeout, LockLost)):
                # lock contention is congestion, not a server fault
                # (the reference maps operation timeouts to 503)
                api = s3err.get("SlowDown")
            elif isinstance(e, TimeoutError):
                # read deadline fired mid-body (slowloris cutoff):
                # 408, and the connection must drop — the unread body
                # bytes would desync keep-alive (socket.timeout is a
                # TimeoutError alias since 3.10)
                api = s3err.get("RequestTimeout")
                self.close_connection = True
            else:
                api = s3err.get("InternalError")
            self._send(api.http_status,
                       s3err.to_xml(api, resource,
                                    getattr(self, "_req_id", "") or ""))

        def _dispatch(self):
            """Trace/audit wrapper around the real dispatcher
            (cmd/http-tracer.go httpTraceAll + cmd/logger/audit.go)."""
            from ..obs import stages as _stages
            from ..obs import trace as _trace
            self._t0_ns = _trace.now_ns()
            # monotonic twin for durations fed into latency windows (a
            # wall-clock step must not record garbage into api_stats)
            self._t0m_ns = time.monotonic_ns()
            self._req_id = uuid.uuid4().hex[:16]
            # correlation root (Dapper-style): every subsystem span this
            # request causes — storage calls, internode RPCs, TPU
            # kernels, even on peer nodes — carries this ID.  The causal
            # tree roots at the request itself: root span id == request
            # id, and every span minted on this thread parents under it
            # until a deeper span pushes its own id
            _trace.set_request_id(self._req_id)
            _trace.set_span_parent(self._req_id)
            # X-ray stage clock, minted beside the request ID and torn
            # down with it; the completion record lands in the flight
            # ring whatever happens below
            _stages.begin()
            self._resp_status = 0
            self._resp_headers = {}
            self._resp_bytes = 0
            self._ttfb_ns = 0
            self._rx_bytes = 0
            self._abort_err = ""
            # request-pool admission (cmd/handler-api.go:29 maxClients):
            # S3 traffic only — admin/metrics/health stay reachable when
            # the data plane is saturated (both reserved namespaces:
            # /minio/health/* is the reference-compatible probe alias)
            throttled = not urllib.parse.urlsplit(self.path).path \
                .startswith(("/minio-tpu/", "/minio/"))
            # capture the pool object: admin SetConfigKV can swap
            # srv._req_sem mid-flight, and acquire/release must pair on
            # the same semaphore
            sem = srv._req_sem if throttled else None
            if sem is not None:
                with _stages.stage("admission"):
                    admitted = self._admit(sem)
            else:
                admitted = True
            if not admitted:
                retry_after = max(1, int(srv.requests_deadline_s))
                try:
                    api = s3err.get("SlowDown")
                    self._send(api.http_status,
                               s3err.to_xml(api, self.path,
                                            self._req_id),
                               headers={"Retry-After": str(retry_after)})
                finally:
                    self.close_connection = True
                    try:    # 503s must show up in trace/audit streams
                        self._record_request()
                    except Exception:  # noqa: BLE001 — the 503 itself
                        pass           # must still reach the client
                    _trace.set_request_id("")
                    _trace.set_span_parent("")
                    _stages.clear()
                return
            # slow-body watchdog: absolute per-request budget for
            # reading the body (size-scaled), armed for everything
            # _dispatch_inner pulls off the wire
            try:
                cl = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                cl = 0
            self.rfile.arm(srv.body_budget_s(cl))
            try:
                try:
                    self._dispatch_inner()
                except (ConnectionError, TimeoutError) as e:
                    # the client died mid-body (reset, stalled socket)
                    # or mid-response: the completion record must still
                    # carry the stage vector and an ``aborted`` marker
                    # instead of settling through the generic close
                    # path with no trace of why (tests/
                    # test_chaos_network.py reset drill).  499 is the
                    # client-closed-request convention when no status
                    # ever went out.
                    self._abort_err = f"aborted: {type(e).__name__}"
                    if not self._resp_status:
                        self._resp_status = 499
                    self.close_connection = True
            finally:
                self.rfile.disarm()
                if sem is not None:
                    sem.release()
                try:
                    self._record_request()
                except Exception:   # noqa: BLE001 — never fail a request
                    pass            # on account of observability
                # keep-alive reuses this thread for the next request —
                # its spans must not inherit this request's ID (nor
                # its stage clock, nor its span parent)
                _trace.set_request_id("")
                _trace.set_span_parent("")
                _stages.clear()

        def _admit(self, sem) -> bool:
            """Request-pool admission: wait up to the deadline for a
            slot, but only while the waiting line is short — a full
            queue sheds IMMEDIATELY (503 + Retry-After) instead of
            parking yet another thread (requestsPool deadline,
            cmd/handler-api.go:29-40)."""
            with srv._req_waiters_mu:
                if srv._req_waiters >= srv.requests_queue_max:
                    return False
                srv._req_waiters += 1
            try:
                return sem.acquire(timeout=srv.requests_deadline_s)
            finally:
                with srv._req_waiters_mu:
                    srv._req_waiters -= 1

        def _record_request(self):
            from ..obs import stages as _stages
            from ..obs import trace as _trace
            dur = _trace.now_ns() - self._t0_ns
            dur_mono = time.monotonic_ns() - self._t0m_ns
            path, bucket, key, query = self._split()
            q1 = {k: v[0] for k, v in query.items()}
            api_name = _api_name(self.command, bucket, key, q1)
            # X-ray completion: close the stage clock against the
            # monotonic request total so the serial vector + ``other``
            # reconciles with it exactly
            clock = _stages.current()
            if clock is not None:
                stage_ns, async_ns, _unattr = clock.finish(dur_mono)
                gating = tuple(clock.gatings)
            else:
                stage_ns, async_ns = {}, {}
                gating = ()
            abort_err = getattr(self, "_abort_err", "")
            srv.flightrec.record(
                self._req_id, api_name, self._resp_status, dur_mono,
                self._rx_bytes, self._resp_bytes,
                stages=tuple(stage_ns.items()),
                async_stages=tuple(async_ns.items()),
                error=abort_err, gating=gating)
            # causal-tree root: the request itself, span id == request
            # id, so every child this request minted (drive ops, rpc
            # legs, quorum gatings — here and on peers) assembles under
            # one root at trace-tree query time.  A compact ring tuple,
            # not a span dict — the idle contract holds.
            _trace.ring_append(self._req_id, self._req_id, "", "http",
                               api_name, self._t0_ns, dur, abort_err,
                               extra=self._resp_status)
            if srv.forensic is not None:
                # Retry-After marks deliberate backpressure (admission
                # or governor sheds) — bounded self-protection, not the
                # breach shape the error-ceiling trigger watches
                srv.forensic.observe_request(
                    self._resp_status,
                    backpressure="Retry-After" in self._resp_headers)
            # metrics-v2 per-API families (cmd/metrics-v2.go
            # getS3RequestsTotalMD / getS3TTFBMetric): request count by
            # api name and the TTFB distribution.  S3 APIs only — the
            # reference scopes these to the S3 router, so health-probe
            # polling and metrics scrapes (reserved /minio-tpu/ and
            # /minio/ namespaces) must not dominate the per-API
            # families; they still ride trace/audit below.
            if not path.startswith(("/minio-tpu/", "/minio/")):
                from ..admin.metrics import GLOBAL as _mtr
                _mtr.inc("mt_s3_requests_api_total", {"api": api_name})
                if self._resp_status >= 400:
                    _mtr.inc("mt_s3_requests_errors_total",
                             {"api": api_name,
                              "status": str(self._resp_status)})
                ttfb = (self._ttfb_ns or dur) / 1e9
                _mtr.observe("mt_s3_ttfb_seconds", {"api": api_name}, ttfb)
                # per-stage latency attribution (the X-ray histogram
                # family): S3 APIs only, same scoping as the per-API
                # counters — ~a dozen stages per API, bounded by the
                # STAGE_NAMES catalog
                for sname, sns in stage_ns.items():
                    _mtr.observe("mt_s3_stage_seconds",
                                 {"api": api_name, "stage": sname},
                                 sns / 1e9)
                for sname, sns in async_ns.items():
                    _mtr.observe("mt_s3_stage_seconds",
                                 {"api": api_name, "stage": sname},
                                 sns / 1e9)
                # last-minute per-API window (mt_s3_api_last_minute_*
                # gauges + admin `top`): S3 APIs only, same scoping as
                # the per-API counter families above; monotonic delta,
                # unlike the wall-clock trace timestamps
                srv.api_stats.record(api_name, dur_mono,
                                     self._rx_bytes + self._resp_bytes)
                # workload attribution (obs/metering.py): same S3-only
                # scoping as the per-API families; the registry bounds
                # label cardinality internally (sketch-gated tenant
                # rows, capped bucket table, keys never become labels)
                if getattr(srv, "metering", None) is not None:
                    srv.metering.charge(
                        bucket=bucket, api=api_name,
                        tenant=getattr(self, "access_key", ""),
                        key=key, status=self._resp_status,
                        rx=self._rx_bytes, tx=self._resp_bytes,
                        dur_ns=dur_mono)
            if srv.trace_hub.active:
                srv.trace_hub.publish(_trace.make_trace(
                    srv.node_name, api_name,
                    method=self.command, path=path,
                    raw_query="&".join(f"{k}={v}" for k, v in q1.items()),
                    client=self.client_address[0],
                    req_headers=dict(self.headers.items()),
                    status_code=self._resp_status,
                    resp_headers=self._resp_headers,
                    input_bytes=self._rx_bytes,
                    output_bytes=self._resp_bytes,
                    start_ns=self._t0_ns, ttfb_ns=self._ttfb_ns,
                    duration_ns=dur, request_id=self._req_id,
                    detail={"stages": stage_ns,
                            "asyncStages": async_ns,
                            "totalNs": dur_mono} if stage_ns else None))
            if srv.audit.enabled:
                srv.audit.publish(srv.audit.entry(
                    api_name=api_name, bucket=bucket, obj=key,
                    status_code=self._resp_status, rx=self._rx_bytes,
                    tx=self._resp_bytes, duration_ns=dur,
                    remote_host=self.client_address[0],
                    request_id=self._req_id,
                    user_agent=self.headers.get("User-Agent", ""),
                    access_key=getattr(self, "access_key", ""),
                    query=q1,
                    req_headers=dict(self.headers.items()),
                    resp_headers=self._resp_headers))

        def _dispatch_inner(self):
            path, bucket, key, query = self._split()
            from ..admin import handlers as admin_handlers
            from ..admin.metrics import GLOBAL as mtr
            try:
                # SSE-C requires TLS, exactly like AWS (the reference's
                # ErrInsecureSSECustomerRequest gate): a client key in
                # the headers of a plaintext request is already leaked
                # — reject before auth, before anything touches it
                from ..crypto import sse as _csse
                if srv.tls is None and (
                        _csse.SSEC_ALGO in self.headers or
                        _csse.SSEC_COPY_ALGO in self.headers):
                    raise S3Error("InsecureSSECustomerRequest")
                if path.startswith(("/minio-tpu/health/",
                                    "/minio/health/")):
                    # healthcheck router (cmd/healthcheck-router.go:40):
                    # unauthenticated, throttle-exempt — k8s probes must
                    # reach it when the server is saturated or keyless.
                    # "/minio/health/*" is the reference's well-known
                    # probe path — existing deployment manifests keep
                    # working unchanged.
                    self._body()
                    return self._health_api(path, query)
                if path == admin_handlers.METRICS_PATH:
                    self._body()  # drain keep-alive body before replying
                    if self.command != "GET":
                        raise S3Error("MethodNotAllowed")
                    return admin_handlers.handle(self, srv, path, query, b"")
                from . import web as web_handlers
                if path == web_handlers.WEBRPC_PATH or \
                        path == web_handlers.ZIP_PATH or \
                        path.startswith((web_handlers.BROWSER_PATH,
                                         web_handlers.UPLOAD_PREFIX,
                                         web_handlers.DOWNLOAD_PREFIX)):
                    # web endpoints authenticate with their own JWT
                    if web_handlers.handle(self, srv, path, query,
                                           self._body):
                        return
                # browser redirect (cmd/generic-handlers.go
                # setBrowserRedirectHandler): an unauthenticated GET /
                # from a web browser lands on the UI, S3 clients (signed
                # or anonymous API calls) are never redirected
                if path == "/" and self.command == "GET" and \
                        "Mozilla" in self.headers.get("User-Agent", "") \
                        and "Authorization" not in self.headers and \
                        "X-Amz-Credential" not in (query or {}) and \
                        "AWSAccessKeyId" not in (query or {}):
                    self._body()
                    self.send_response(303)
                    self.send_header("Location", web_handlers.BROWSER_PATH)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if self._try_stream_put(path, bucket, key, query):
                    return
                payload = self._body()
                self._rx_bytes = len(payload)
                mtr.inc("mt_s3_rx_bytes_total", value=len(payload))
                payload = self._auth(path, query, payload)
                if path.startswith("/minio-tpu/"):
                    if admin_handlers.handle(self, srv, path, query,
                                             payload):
                        return
                if bucket in ("minio-tpu", "minio"):
                    # reserved namespaces (isMinioReservedBucket,
                    # cmd/generic-handlers.go): admin/metrics own
                    # "minio-tpu"; "minio" is reserved exactly like the
                    # reference reserves it, so the unauthenticated
                    # /minio/health/* probe router can never shadow a
                    # real bucket's objects
                    raise S3Error("AccessDenied")
                if not bucket:
                    if self.command == "POST":
                        return self._sts_api(payload)
                    return self._list_buckets()
                if not _BUCKET_RE.match(bucket):
                    raise S3Error("InvalidBucketName")
                try:
                    if key:
                        return self._object_api(bucket, key, query,
                                                payload)
                    return self._bucket_api(bucket, query, payload)
                except ol.BucketNotFound:
                    # federated bucket homed on another cluster: 307 to
                    # its owner (cmd/handler-utils.go redirect path)
                    if srv.federation is not None:
                        rec = srv.federation.lookup_other(bucket)
                        if rec is not None:
                            u = urllib.parse.urlsplit(self.path)
                            loc = (f"http://{rec.host}:{rec.port}"
                                   f"{u.path}"
                                   + (f"?{u.query}" if u.query else ""))
                            return self._send(
                                307, b"", headers={"Location": loc})
                    raise
            except ConnectionError:
                # connection death (client reset mid-body or
                # mid-response): there is nobody to send XML to —
                # propagate to the dispatch abort catch, which stamps
                # the flight-recorder row ``aborted: <Exc>``.  A bare
                # TimeoutError is NOT a death: the stalled-socket
                # watchdog fires on a slow-but-alive client, whose
                # socket still deserves the 408 XML below.
                raise
            except Exception as e:  # noqa: BLE001 — every error becomes XML
                self._fail(e, path)

        def _handle(self):
            """Active-request bookkeeping around _dispatch: the graceful
            drain in stop() waits for connections in this window (and
            only these) before severing; once the server is stopping, a
            finishing request closes its connection instead of parking
            for another keep-alive round."""
            with srv._conns_mu:
                srv._active_conns.add(self.connection)
            try:
                self._dispatch()
            finally:
                with srv._conns_mu:
                    srv._active_conns.discard(self.connection)
                if getattr(srv, "_stopping", False):
                    self.close_connection = True

        # PATCH/OPTIONS etc. flow through the same dispatcher and come
        # back as the S3 MethodNotAllowed XML error — the stdlib's raw
        # 501 would leak a non-S3 error shape to clients
        do_GET = do_PUT = do_HEAD = do_DELETE = do_POST = do_PATCH = \
            do_OPTIONS = lambda self: self._handle()

        # -- STS (cmd/sts-handlers.go) -------------------------------------

        STS_NS = "https://sts.amazonaws.com/doc/2011-06-15/"

        def _sts_fail(self, code: str, msg: str = ""):
            root = ET.Element("ErrorResponse", xmlns=self.STS_NS)
            err = ET.SubElement(root, "Error")
            ET.SubElement(err, "Type").text = "Sender"
            ET.SubElement(err, "Code").text = code
            ET.SubElement(err, "Message").text = msg or code
            status = 403 if code in ("AccessDenied", "ExpiredToken") \
                else 400
            self._send(status, _xml(root))

        def _sts_api(self, payload: bytes):
            from ..iam import sts as _sts
            form = {k: v[0] for k, v in urllib.parse.parse_qs(
                payload.decode("utf-8", "replace"),
                keep_blank_values=True).items()}
            action = form.get("Action", "")
            if action in ("AssumeRoleWithWebIdentity",
                          "AssumeRoleWithClientGrants"):
                return self._sts_web_identity(form, action)
            if action == "AssumeRoleWithLDAPIdentity":
                return self._sts_ldap_identity(form)
            if action != "AssumeRole":
                return self._sts_fail("InvalidAction", action)
            if not self.access_key:
                return self._sts_fail("AccessDenied",
                                      "request must be signed")
            try:
                duration = int(form.get("DurationSeconds",
                                        str(_sts.DEFAULT_DURATION_S)))
            except ValueError:
                return self._sts_fail("InvalidParameterValue",
                                      "DurationSeconds")
            policy = form.get("Policy") or None
            try:
                creds = srv.iam.assume_role(self.access_key, duration,
                                            policy)
            except _sts.STSError as e:
                return self._sts_fail(e.code, str(e))
            root = ET.Element("AssumeRoleResponse", xmlns=self.STS_NS)
            result = ET.SubElement(root, "AssumeRoleResult")
            ce = ET.SubElement(result, "Credentials")
            ET.SubElement(ce, "AccessKeyId").text = creds.access_key
            ET.SubElement(ce, "SecretAccessKey").text = creds.secret_key
            ET.SubElement(ce, "SessionToken").text = creds.session_token
            ET.SubElement(ce, "Expiration").text = \
                datetime.datetime.fromtimestamp(
                    creds.expiration, datetime.timezone.utc).strftime(
                        "%Y-%m-%dT%H:%M:%SZ")
            meta = ET.SubElement(root, "ResponseMetadata")
            ET.SubElement(meta, "RequestId").text = uuid.uuid4().hex[:16]
            self._send(200, _xml(root))

        def _sts_ldap_identity(self, form: dict):
            """AssumeRoleWithLDAPIdentity (cmd/sts-handlers.go:436):
            verify the username/password against the configured
            directory, mint temp creds carrying the LDAP-mapped
            policies.  Unsigned by design — the password is the
            credential."""
            from ..iam import ldap as _ldap
            from ..iam import sts as _sts
            if srv.ldap is None or not srv.ldap.config.enabled:
                return self._sts_fail(
                    "NotImplemented",
                    "no LDAP provider configured (identity_ldap)")
            username = form.get("LDAPUsername", "")
            password = form.get("LDAPPassword", "")
            if not username or not password:
                return self._sts_fail(
                    "MissingParameter",
                    "LDAPUsername and LDAPPassword cannot be empty")
            policy = form.get("Policy") or None
            if policy and len(policy) > 2048:
                return self._sts_fail(
                    "InvalidParameterValue",
                    "session policy exceeds 2048 characters")
            try:
                duration = int(form.get(
                    "DurationSeconds", str(srv.ldap.config.sts_expiry_s)))
            except ValueError:
                return self._sts_fail("InvalidParameterValue",
                                      "DurationSeconds")
            try:
                user_dn, groups = srv.ldap.bind(username, password)
            except _ldap.LDAPError as e:
                return self._sts_fail("InvalidParameterValue",
                                      f"LDAP server error: {e}")
            try:
                creds = srv.iam.assume_role_ldap_identity(
                    user_dn, username, groups, duration,
                    session_policy=policy)
            except _sts.STSError as e:
                return self._sts_fail(e.code, str(e))
            except Exception as e:  # noqa: BLE001 — surface as STS error
                return self._sts_fail("InvalidParameterValue", str(e))
            root = ET.Element("AssumeRoleWithLDAPIdentityResponse",
                              xmlns=self.STS_NS)
            result = ET.SubElement(
                root, "AssumeRoleWithLDAPIdentityResult")
            ce = ET.SubElement(result, "Credentials")
            ET.SubElement(ce, "AccessKeyId").text = creds.access_key
            ET.SubElement(ce, "SecretAccessKey").text = creds.secret_key
            ET.SubElement(ce, "SessionToken").text = creds.session_token
            ET.SubElement(ce, "Expiration").text = \
                datetime.datetime.fromtimestamp(
                    creds.expiration, datetime.timezone.utc).strftime(
                        "%Y-%m-%dT%H:%M:%SZ")
            meta = ET.SubElement(root, "ResponseMetadata")
            ET.SubElement(meta, "RequestId").text = uuid.uuid4().hex[:16]
            self._send(200, _xml(root))

        def _sts_web_identity(self, form: dict, action: str):
            """AssumeRoleWithWebIdentity (cmd/sts-handlers.go): validate
            the provider-issued JWT, map the policy claim, mint creds.
            Unsigned by design — the JWT is the credential."""
            from ..iam import openid as _oidc
            from ..iam import sts as _sts
            if srv.openid is None:
                return self._sts_fail(
                    "NotImplemented",
                    "no OpenID provider configured (identity_openid)")
            token = form.get("WebIdentityToken") or form.get("Token", "")
            if not token:
                return self._sts_fail("InvalidParameterValue",
                                      "WebIdentityToken required")
            try:
                duration = int(form.get("DurationSeconds",
                                        str(_sts.DEFAULT_DURATION_S)))
            except ValueError:
                return self._sts_fail("InvalidParameterValue",
                                      "DurationSeconds")
            try:
                claims = srv.openid.authenticate(token)
            except _oidc.OpenIDError as e:
                return self._sts_fail("AccessDenied", str(e))
            policies = srv.openid.policies_of(claims)
            if not policies:
                return self._sts_fail(
                    "AccessDenied",
                    f"token carries no {srv.openid.claim_name!r} claim")
            from ..iam.sys import NoSuchPolicy
            try:
                creds = srv.iam.assume_role_web_identity(
                    claims["sub"], policies, duration)
            except NoSuchPolicy as e:
                return self._sts_fail("AccessDenied",
                                      f"unknown policy: {e}")
            except _sts.STSError as e:
                return self._sts_fail(e.code, str(e))
            root = ET.Element(f"{action}Response", xmlns=self.STS_NS)
            result = ET.SubElement(root, f"{action}Result")
            ce = ET.SubElement(result, "Credentials")
            ET.SubElement(ce, "AccessKeyId").text = creds.access_key
            ET.SubElement(ce, "SecretAccessKey").text = creds.secret_key
            ET.SubElement(ce, "SessionToken").text = creds.session_token
            ET.SubElement(ce, "Expiration").text = \
                datetime.datetime.fromtimestamp(
                    creds.expiration, datetime.timezone.utc).strftime(
                        "%Y-%m-%dT%H:%M:%SZ")
            ET.SubElement(result, "SubjectFromWebIdentityToken").text = \
                claims["sub"]
            meta = ET.SubElement(root, "ResponseMetadata")
            ET.SubElement(meta, "RequestId").text = uuid.uuid4().hex[:16]
            self._send(200, _xml(root))

        def _check_session_token(self):
            """Temp credentials must present their session token on every
            request (checkClaimsFromToken, cmd/auth-handler.go)."""
            from ..iam import sts as _sts
            if not self.access_key:
                return
            try:
                u = srv.iam.get_user(self.access_key)
            except Exception:  # noqa: BLE001 — root or unknown: no claims
                return
            if not (u.parent_user and u.expiration):
                return
            tok = self.headers.get("x-amz-security-token", "") or \
                self._query_token
            if not tok:
                raise S3Error("AccessDenied")
            try:
                claims = _sts.verify_token(tok, srv.iam.root.secret_key)
            except _sts.STSError as e:
                raise S3Error("ExpiredToken" if e.code == "ExpiredToken"
                              else "AccessDenied") from e
            if claims.get("accessKey") != self.access_key:
                raise S3Error("AccessDenied")

        # -- healthcheck router (cmd/healthcheck-router.go:40) ------------

        def _health_api(self, path, query):
            if self.command not in ("GET", "HEAD"):
                raise S3Error("MethodNotAllowed")
            leaf = path.split("/health/", 1)[1]
            status = 200
            headers = {}
            if leaf == "cluster":
                # readiness for traffic incl. maintenance pre-check
                # (cmd/healthcheck-handler.go:28-66 ClusterCheckHandler)
                maint = (query or {}).get("maintenance",
                                          [""])[0] == "true"
                h = srv.layer.health(maintenance=maint)
                if h["write_quorum"]:
                    headers["X-Minio-Write-Quorum"] = \
                        str(h["write_quorum"])
                if not h["healthy"]:
                    if h["healing_drives"]:
                        headers["X-Minio-Healing-Drives"] = \
                            str(h["healing_drives"])
                    # maintenance probe: 412 tells the orchestrator the
                    # node can NOT be safely taken down
                    status = 412 if maint else 503
            elif leaf in ("live", "ready"):
                # process-level probes: always 200 while the process
                # serves, exactly like the reference
                # (cmd/healthcheck-handler.go:69-84 returns success
                # unconditionally); a stopping server only annotates
                # the informational offline header
                if getattr(srv, "_stopping", False):
                    headers["X-Minio-Server-Status"] = "offline"
            else:
                raise S3Error("NoSuchKey")
            self._send(status, b"", headers=headers)

        # -- service / bucket APIs ----------------------------------------

        # bucket/object handler families live in handlers_bucket.py /
        # handlers_object.py (split from this file, attached below)

    # handler-family modules (split from this file): plain functions
    # taking the handler instance; srv rides on the class
    from . import handlers_bucket, handlers_object
    Handler.srv = srv
    Handler.TAG_KEY = handlers_object.TAG_KEY
    for _mod in (handlers_bucket, handlers_object):
        for _name in _mod.HANDLERS:
            setattr(Handler, _name, getattr(_mod, _name))

    return Handler


def _actual_size(oi) -> int:
    """Client-visible size (GetActualSize, cmd/object-api-utils.go): the
    pre-compression size for compressed objects, the DARE-plaintext size
    for encrypted-only objects, else the stored size."""
    from ..crypto import sse as csse
    raw = oi.user_defined.get(csse.META_ACTUAL_SIZE)
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    if csse.is_encrypted(oi.user_defined):
        try:
            return csse.decrypted_size(oi.user_defined, oi.size, oi.parts)
        except Exception:  # noqa: BLE001 — corrupt meta: report stored size
            pass
    return oi.size


def _parse_range(spec: str) -> tuple[int, int]:
    """HTTP Range -> (offset, length) without knowing the size
    (cmd/httprange.go); negative offset = suffix, length -1 = to-end.
    Size-dependent validation/clamping happens in the object layer, so a
    ranged GET costs a single quorum metadata read."""
    m = re.match(r"^bytes=(\d*)-(\d*)$", spec.strip())
    if not m:
        raise S3Error("InvalidRange")
    first, last = m.group(1), m.group(2)
    if first == "" and last == "":
        raise S3Error("InvalidRange")
    if first == "":  # suffix range: last N bytes
        n = int(last)
        if n == 0:
            raise S3Error("InvalidRange")
        return -n, -1
    start = int(first)
    if last == "":
        return start, -1
    end = int(last)
    if end < start:
        raise S3Error("InvalidRange")
    return start, end - start + 1
