"""S3 API HTTP server (cmd/api-router.go:82 + cmd/object-handlers.go /
cmd/bucket-handlers.go).

Path-style S3 over a threading HTTP server: the L1/L3 frontend of the
framework.  Handlers authenticate (SigV4 header or presigned), map the
route to an ObjectLayer call, and render S3 XML.  The compute-heavy body
(erasure encode/decode) happens inside the object layer on TPU.
"""

from __future__ import annotations

import datetime
import email.utils
import hashlib
import re
import socket
import threading
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..iam import policy as iampol
from ..objectlayer import interface as ol
from ..objectlayer.bucket_meta import BucketMetadataSys
from . import errors as s3err
from . import sigv4

MAX_OBJECT_SIZE = 5 * 1024 * 1024 * 1024 * 1024  # 5 TiB (docs/minio-limits.md)
S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"

_BUCKET_RE = re.compile(r"^[a-z0-9][a-z0-9.\-]{1,61}[a-z0-9]$")


class S3Error(Exception):
    def __init__(self, code: str):
        super().__init__(code)
        self.api = s3err.get(code)


def _http_date(ns: int) -> str:
    return email.utils.formatdate(ns / 1e9, usegmt=True)


def _iso_date(ns: int) -> str:
    return datetime.datetime.fromtimestamp(
        ns / 1e9, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


def _xml(root: ET.Element) -> bytes:
    return (b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root))


class S3Server:
    """Wires an ObjectLayer + credentials into an HTTP server."""

    def __init__(self, object_layer, access_key: str = "minioadmin",
                 secret_key: str = "minioadmin", region: str = "us-east-1",
                 host: str = "127.0.0.1", port: int = 0,
                 max_body_size: int = 1024 ** 3, iam=None):
        self.layer = object_layer
        if iam is None:
            from ..iam.sys import IAMSys
            iam = IAMSys(object_layer, access_key, secret_key)
        self.iam = iam
        self.region = region
        self.max_body_size = max_body_size
        self.bucket_meta = BucketMetadataSys(object_layer)
        from ..utils.kvconfig import Config
        self.config = Config(object_layer)
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"


def _make_handler(srv: S3Server):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "MinioTPU"

        # -- plumbing ------------------------------------------------------

        def log_message(self, fmt, *args):  # quiet; tracing hooks later
            pass

        def _split(self):
            u = urllib.parse.urlsplit(self.path)
            path = urllib.parse.unquote(u.path)
            query = urllib.parse.parse_qs(u.query, keep_blank_values=True)
            parts = path.lstrip("/").split("/", 1)
            bucket = parts[0]
            key = parts[1] if len(parts) > 1 else ""
            return path, bucket, key, query

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length") or 0)
            if n > srv.max_body_size:
                # reject before buffering: unauthenticated clients must not
                # be able to force huge allocations
                raise S3Error("EntityTooLarge")
            return self.rfile.read(n) if n else b""

        def _auth(self, path, query, payload: bytes) -> bytes:
            """Authenticate; returns the effective payload (aws-chunked
            bodies are signature-verified per chunk and de-framed).  Sets
            self.access_key for authorization."""
            lookup = srv.iam.lookup_secret
            hdrs = {k: v for k, v in self.headers.items()}
            try:
                if "X-Amz-Signature" in query:
                    self.access_key = sigv4.verify_presigned(
                        lookup, self.command, path, query, hdrs,
                        region=srv.region)
                    return payload
                sha = self.headers.get("x-amz-content-sha256",
                                       sigv4.UNSIGNED_PAYLOAD)
                if sha == sigv4.STREAMING_PAYLOAD:
                    self.access_key, key, seed, amz_date, scope = \
                        sigv4.verify_request_streaming(
                            lookup, self.command, path, query, hdrs,
                            region=srv.region)
                    return sigv4.decode_chunked_payload(
                        payload, key, seed, amz_date, scope)
                if sha != sigv4.UNSIGNED_PAYLOAD:
                    got = hashlib.sha256(payload).hexdigest()
                    if got != sha:
                        raise S3Error("BadDigest")
                self.access_key = sigv4.verify_request(
                    lookup, self.command, path, query, hdrs, sha,
                    region=srv.region)
                return payload
            except sigv4.SigV4Error as e:
                raise S3Error(e.code) from e

        def _allow(self, action: str, resource: str = "") -> None:
            """Authorize the authenticated key for an S3 action
            (checkRequestAuthType -> IAMSys.IsAllowed)."""
            if not srv.iam.is_allowed(self.access_key, action, resource):
                raise S3Error("AccessDenied")

        def _send(self, status: int, body: bytes = b"",
                  content_type: str = "application/xml",
                  headers: dict | None = None,
                  content_length: int | None = None):
            """content_length: explicit value for HEAD responses (body is
            not sent but the header must describe the entity)."""
            from ..admin.metrics import GLOBAL as mtr
            mtr.inc("mt_s3_requests_total",
                    {"method": self.command, "status": str(status)})
            mtr.inc("mt_s3_tx_bytes_total", value=len(body))
            self.send_response(status)
            self.send_header("x-amz-request-id", uuid.uuid4().hex[:16])
            self.send_header("Server", "MinioTPU")
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Type", content_type)
            if content_length is not None:
                self.send_header("Content-Length", str(content_length))
            else:
                self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body and self.command != "HEAD":
                self.wfile.write(body)

        def _fail(self, e: Exception, resource: str = ""):
            if isinstance(e, S3Error):
                api = e.api
            elif isinstance(e, ol.ObjectLayerError):
                api = s3err.from_object_error(e)
            else:
                api = s3err.get("InternalError")
            self._send(api.http_status, s3err.to_xml(api, resource))

        def _dispatch(self):
            path, bucket, key, query = self._split()
            from ..admin import handlers as admin_handlers
            from ..admin.metrics import GLOBAL as mtr
            try:
                if path == admin_handlers.METRICS_PATH:
                    self._body()  # drain keep-alive body before replying
                    if self.command != "GET":
                        raise S3Error("MethodNotAllowed")
                    return admin_handlers.handle(self, srv, path, query, b"")
                payload = self._body()
                mtr.inc("mt_s3_rx_bytes_total", value=len(payload))
                payload = self._auth(path, query, payload)
                if path.startswith("/minio-tpu/"):
                    if admin_handlers.handle(self, srv, path, query,
                                             payload):
                        return
                if bucket == "minio-tpu":
                    # reserved namespace (isMinioReservedBucket analog):
                    # admin/metrics own this prefix; never an S3 bucket
                    raise S3Error("AccessDenied")
                if not bucket:
                    return self._list_buckets()
                if not _BUCKET_RE.match(bucket):
                    raise S3Error("InvalidBucketName")
                if key:
                    return self._object_api(bucket, key, query, payload)
                return self._bucket_api(bucket, query, payload)
            except Exception as e:  # noqa: BLE001 — every error becomes XML
                self._fail(e, path)

        do_GET = do_PUT = do_HEAD = do_DELETE = do_POST = \
            lambda self: self._dispatch()

        # -- service / bucket APIs ----------------------------------------

        def _list_buckets(self):
            if self.command != "GET":
                raise S3Error("MethodNotAllowed")
            self._allow(iampol.LIST_ALL_MY_BUCKETS)
            root = ET.Element("ListAllMyBucketsResult", xmlns=S3_NS)
            owner = ET.SubElement(root, "Owner")
            ET.SubElement(owner, "ID").text = "minio-tpu"
            ET.SubElement(owner, "DisplayName").text = "minio-tpu"
            buckets = ET.SubElement(root, "Buckets")
            for b in srv.layer.list_buckets():
                be = ET.SubElement(buckets, "Bucket")
                ET.SubElement(be, "Name").text = b.name
                ET.SubElement(be, "CreationDate").text = _iso_date(b.created)
            self._send(200, _xml(root))

        def _bucket_api(self, bucket, query, payload):
            cmd = self.command
            if cmd == "PUT" and "versioning" in query:
                self._allow(iampol.PUT_BUCKET_VERSIONING, bucket)
                return self._put_versioning(bucket, payload)
            if cmd == "GET" and "versioning" in query:
                self._allow(iampol.GET_BUCKET_VERSIONING, bucket)
                return self._get_versioning(bucket)
            if cmd == "GET" and "location" in query:
                self._allow(iampol.GET_BUCKET_LOCATION, bucket)
                root = ET.Element("LocationConstraint", xmlns=S3_NS)
                root.text = srv.region
                srv.layer.get_bucket_info(bucket)
                return self._send(200, _xml(root))
            if cmd == "GET" and "versions" in query:
                self._allow(iampol.LIST_BUCKET_VERSIONS, bucket)
                return self._list_object_versions(bucket, query)
            if cmd == "POST" and "delete" in query:
                return self._delete_objects(bucket, payload)
            if cmd == "GET" and "uploads" in query:
                self._allow(iampol.LIST_MULTIPART_UPLOADS, bucket)
                return self._list_uploads(bucket, query)
            if cmd == "PUT":
                self._allow(iampol.CREATE_BUCKET, bucket)
                srv.layer.make_bucket(bucket)
                return self._send(200, headers={"Location": f"/{bucket}"})
            if cmd == "HEAD":
                self._allow(iampol.LIST_BUCKET, bucket)
                srv.layer.get_bucket_info(bucket)
                return self._send(200)
            if cmd == "DELETE":
                self._allow(iampol.DELETE_BUCKET, bucket)
                srv.layer.delete_bucket(bucket)
                srv.bucket_meta.drop(bucket)
                return self._send(204)
            if cmd == "GET":
                self._allow(iampol.LIST_BUCKET, bucket)
                return self._list_objects(bucket, query)
            raise S3Error("MethodNotAllowed")

        def _put_versioning(self, bucket, payload):
            srv.layer.get_bucket_info(bucket)
            try:
                root = ET.fromstring(payload)
                status = root.findtext(f"{{{S3_NS}}}Status") or \
                    root.findtext("Status") or ""
            except ET.ParseError as e:
                raise S3Error("MalformedXML") from e
            srv.bucket_meta.set_versioning(bucket, status == "Enabled")
            self._send(200)

        def _get_versioning(self, bucket):
            srv.layer.get_bucket_info(bucket)
            root = ET.Element("VersioningConfiguration", xmlns=S3_NS)
            doc = srv.bucket_meta.get(bucket).get("versioning")
            if doc:
                ET.SubElement(root, "Status").text = doc["status"]
            self._send(200, _xml(root))

        def _list_objects(self, bucket, query):
            q1 = {k: v[0] for k, v in query.items()}
            v2 = q1.get("list-type") == "2"
            prefix = q1.get("prefix", "")
            delimiter = q1.get("delimiter", "")
            max_keys = min(int(q1.get("max-keys", 1000) or 1000), 1000)
            marker = q1.get("continuation-token" if v2 else "marker", "") \
                or q1.get("start-after", "")
            res = srv.layer.list_objects(bucket, prefix, marker, delimiter,
                                         max_keys)
            name = "ListBucketResult"
            root = ET.Element(name, xmlns=S3_NS)
            ET.SubElement(root, "Name").text = bucket
            ET.SubElement(root, "Prefix").text = prefix
            if delimiter:
                ET.SubElement(root, "Delimiter").text = delimiter
            ET.SubElement(root, "MaxKeys").text = str(max_keys)
            ET.SubElement(root, "IsTruncated").text = \
                "true" if res.is_truncated else "false"
            if v2:
                ET.SubElement(root, "KeyCount").text = \
                    str(len(res.objects) + len(res.prefixes))
                if res.is_truncated:
                    ET.SubElement(root, "NextContinuationToken").text = \
                        res.next_marker
            elif res.is_truncated:
                ET.SubElement(root, "NextMarker").text = res.next_marker
            for o in res.objects:
                c = ET.SubElement(root, "Contents")
                ET.SubElement(c, "Key").text = o.name
                ET.SubElement(c, "LastModified").text = _iso_date(o.mod_time)
                ET.SubElement(c, "ETag").text = f'"{o.etag}"'
                ET.SubElement(c, "Size").text = str(o.size)
                ET.SubElement(c, "StorageClass").text = "STANDARD"
            for p in res.prefixes:
                cp = ET.SubElement(root, "CommonPrefixes")
                ET.SubElement(cp, "Prefix").text = p
            self._send(200, _xml(root))

        def _list_object_versions(self, bucket, query):
            q1 = {k: v[0] for k, v in query.items()}
            prefix = q1.get("prefix", "")
            versions = srv.layer.list_object_versions(bucket, prefix)
            root = ET.Element("ListVersionsResult", xmlns=S3_NS)
            ET.SubElement(root, "Name").text = bucket
            ET.SubElement(root, "Prefix").text = prefix
            ET.SubElement(root, "IsTruncated").text = "false"
            for o in versions:
                tag = "DeleteMarker" if o.delete_marker else "Version"
                v = ET.SubElement(root, tag)
                ET.SubElement(v, "Key").text = o.name
                ET.SubElement(v, "VersionId").text = o.version_id or "null"
                ET.SubElement(v, "IsLatest").text = \
                    "true" if o.is_latest else "false"
                ET.SubElement(v, "LastModified").text = _iso_date(o.mod_time)
                if not o.delete_marker:
                    ET.SubElement(v, "ETag").text = f'"{o.etag}"'
                    ET.SubElement(v, "Size").text = str(o.size)
                    ET.SubElement(v, "StorageClass").text = "STANDARD"
            self._send(200, _xml(root))

        def _list_uploads(self, bucket, query):
            q1 = {k: v[0] for k, v in query.items()}
            uploads = srv.layer.list_multipart_uploads(
                bucket, q1.get("prefix", ""))
            root = ET.Element("ListMultipartUploadsResult", xmlns=S3_NS)
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "IsTruncated").text = "false"
            for u in uploads:
                ue = ET.SubElement(root, "Upload")
                ET.SubElement(ue, "Key").text = u.object_name
                ET.SubElement(ue, "UploadId").text = u.upload_id
            self._send(200, _xml(root))

        def _delete_objects(self, bucket, payload):
            try:
                root = ET.fromstring(payload)
            except ET.ParseError as e:
                raise S3Error("MalformedXML") from e
            ns = f"{{{S3_NS}}}"
            quiet = (root.findtext(f"{ns}Quiet") or
                     root.findtext("Quiet") or "") == "true"
            out = ET.Element("DeleteResult", xmlns=S3_NS)
            versioned = srv.bucket_meta.versioning_enabled(bucket)
            for obj in (root.findall(f"{ns}Object") +
                        root.findall("Object")):
                key = obj.findtext(f"{ns}Key") or obj.findtext("Key")
                vid = obj.findtext(f"{ns}VersionId") or \
                    obj.findtext("VersionId")
                try:
                    self._allow(iampol.DELETE_OBJECT, f"{bucket}/{key}")
                    res = srv.layer.delete_object(
                        bucket, key,
                        ol.ObjectOptions(version_id=vid,
                                         versioned=versioned))
                    if not quiet:
                        d = ET.SubElement(out, "Deleted")
                        ET.SubElement(d, "Key").text = key
                        if res.delete_marker:
                            ET.SubElement(d, "DeleteMarker").text = "true"
                            ET.SubElement(d,
                                          "DeleteMarkerVersionId").text = \
                                res.version_id
                except Exception as e:  # noqa: BLE001
                    if isinstance(e, S3Error):
                        api = e.api
                    elif isinstance(e, ol.ObjectLayerError):
                        api = s3err.from_object_error(e)
                    else:
                        api = s3err.get("InternalError")
                    err = ET.SubElement(out, "Error")
                    ET.SubElement(err, "Key").text = key
                    ET.SubElement(err, "Code").text = api.code
                    ET.SubElement(err, "Message").text = api.description
            self._send(200, _xml(out))

        # -- object APIs ---------------------------------------------------

        def _object_api(self, bucket, key, query, payload):
            cmd = self.command
            resource = f"{bucket}/{key}"
            if cmd == "POST" and "uploads" in query:
                self._allow(iampol.PUT_OBJECT, resource)
                return self._create_multipart(bucket, key)
            if cmd == "POST" and "uploadId" in query:
                self._allow(iampol.PUT_OBJECT, resource)
                return self._complete_multipart(bucket, key, query, payload)
            if cmd == "PUT" and "uploadId" in query:
                self._allow(iampol.PUT_OBJECT, resource)
                return self._upload_part(bucket, key, query, payload)
            if cmd == "DELETE" and "uploadId" in query:
                self._allow(iampol.ABORT_MULTIPART, resource)
                srv.layer.abort_multipart_upload(bucket, key,
                                                 query["uploadId"][0])
                return self._send(204)
            if cmd == "GET" and "uploadId" in query:
                self._allow(iampol.LIST_PARTS, resource)
                return self._list_parts(bucket, key, query)
            if cmd == "PUT":
                self._allow(iampol.PUT_OBJECT, resource)
                return self._put_object(bucket, key, query, payload)
            if cmd in ("GET", "HEAD"):
                self._allow(
                    iampol.GET_OBJECT_VERSION if query.get("versionId")
                    else iampol.GET_OBJECT, resource)
                return self._get_object(bucket, key, query,
                                        head=(cmd == "HEAD"))
            if cmd == "DELETE":
                self._allow(
                    iampol.DELETE_OBJECT_VERSION if query.get("versionId")
                    else iampol.DELETE_OBJECT, resource)
                return self._delete_object(bucket, key, query)
            raise S3Error("MethodNotAllowed")

        def _create_multipart(self, bucket, key):
            user_defined = {}
            ct = self.headers.get("Content-Type")
            if ct:
                user_defined["content-type"] = ct
            for h, v in self.headers.items():
                if h.lower().startswith("x-amz-meta-"):
                    user_defined[h.lower()] = v
            versioned = srv.bucket_meta.versioning_enabled(bucket)
            uid = srv.layer.new_multipart_upload(
                bucket, key, ol.PutObjectOptions(
                    user_defined=user_defined, versioned=versioned))
            root = ET.Element("InitiateMultipartUploadResult", xmlns=S3_NS)
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "Key").text = key
            ET.SubElement(root, "UploadId").text = uid
            self._send(200, _xml(root))

        def _upload_part(self, bucket, key, query, payload):
            uid = query["uploadId"][0]
            try:
                part_num = int(query["partNumber"][0])
            except (KeyError, ValueError) as e:
                raise S3Error("InvalidArgument") from e
            pi = srv.layer.put_object_part(bucket, key, uid, part_num,
                                           payload)
            self._send(200, headers={"ETag": f'"{pi.etag}"'})

        def _complete_multipart(self, bucket, key, query, payload):
            uid = query["uploadId"][0]
            try:
                root = ET.fromstring(payload)
            except ET.ParseError as e:
                raise S3Error("MalformedXML") from e
            ns = f"{{{S3_NS}}}"
            parts = []
            for p in root.findall(f"{ns}Part") + root.findall("Part"):
                num = p.findtext(f"{ns}PartNumber") or \
                    p.findtext("PartNumber")
                etag = p.findtext(f"{ns}ETag") or p.findtext("ETag") or ""
                if num is None or not num.isdigit():
                    raise S3Error("MalformedXML")
                parts.append((int(num), etag.strip('"')))
            oi = srv.layer.complete_multipart_upload(bucket, key, uid, parts)
            out = ET.Element("CompleteMultipartUploadResult", xmlns=S3_NS)
            ET.SubElement(out, "Location").text = \
                f"{srv.endpoint}/{bucket}/{key}"
            ET.SubElement(out, "Bucket").text = bucket
            ET.SubElement(out, "Key").text = key
            ET.SubElement(out, "ETag").text = f'"{oi.etag}"'
            hdrs = {}
            if oi.version_id:
                hdrs["x-amz-version-id"] = oi.version_id
            self._send(200, _xml(out), headers=hdrs)

        def _list_parts(self, bucket, key, query):
            uid = query["uploadId"][0]
            parts = srv.layer.list_object_parts(bucket, key, uid)
            root = ET.Element("ListPartsResult", xmlns=S3_NS)
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "Key").text = key
            ET.SubElement(root, "UploadId").text = uid
            ET.SubElement(root, "IsTruncated").text = "false"
            for p in parts:
                pe = ET.SubElement(root, "Part")
                ET.SubElement(pe, "PartNumber").text = str(p.part_number)
                ET.SubElement(pe, "ETag").text = f'"{p.etag}"'
                ET.SubElement(pe, "Size").text = str(p.size)
            self._send(200, _xml(root))

        def _put_object(self, bucket, key, query, payload):
            if "Content-Length" not in self.headers:
                raise S3Error("MissingContentLength")
            if len(payload) > MAX_OBJECT_SIZE:
                raise S3Error("EntityTooLarge")
            md5_hdr = self.headers.get("Content-MD5")
            if md5_hdr:
                import base64
                try:
                    want = base64.b64decode(md5_hdr)
                except Exception as e:
                    raise S3Error("InvalidDigest") from e
                if hashlib.md5(payload).digest() != want:
                    raise S3Error("BadDigest")
            user_defined = {}
            ct = self.headers.get("Content-Type")
            if ct:
                user_defined["content-type"] = ct
            for h, v in self.headers.items():
                if h.lower().startswith("x-amz-meta-"):
                    user_defined[h.lower()] = v
            versioned = srv.bucket_meta.versioning_enabled(bucket)
            oi = srv.layer.put_object(
                bucket, key, payload,
                ol.PutObjectOptions(user_defined=user_defined,
                                    versioned=versioned))
            hdrs = {"ETag": f'"{oi.etag}"'}
            if oi.version_id:
                hdrs["x-amz-version-id"] = oi.version_id
            self._send(200, headers=hdrs)

        def _get_object(self, bucket, key, query, head: bool):
            q1 = {k: v[0] for k, v in query.items()}
            vid = q1.get("versionId")
            if vid == "null":
                vid = ""
            opts = ol.ObjectOptions(version_id=vid)
            rng = self.headers.get("Range")
            offset, length = 0, -1
            try:
                if head:
                    oi = srv.layer.get_object_info(bucket, key, opts)
                    data = None
                else:
                    if rng:
                        offset, length = _parse_range(rng)
                    oi, data = srv.layer.get_object(bucket, key, offset,
                                                    length, opts)
            except ol.MethodNotAllowed:
                # delete marker (cmd/object-handlers.go: 405 + header)
                return self._send(
                    405, s3err.to_xml(s3err.get("MethodNotAllowed")),
                    headers={"x-amz-delete-marker": "true"})
            hdrs = {
                "ETag": f'"{oi.etag}"',
                "Last-Modified": _http_date(oi.mod_time),
                "Accept-Ranges": "bytes",
            }
            if oi.version_id:
                hdrs["x-amz-version-id"] = oi.version_id
            for k2, v in oi.user_defined.items():
                if k2.startswith("x-amz-meta-"):
                    hdrs[k2] = v
            ct = oi.content_type or "binary/octet-stream"
            if head:
                if oi.delete_marker:
                    hdrs = {"x-amz-delete-marker": "true"}
                    if oi.version_id:
                        hdrs["x-amz-version-id"] = oi.version_id
                    return self._send(405, b"", headers=hdrs,
                                      content_length=0)
                return self._send(200, b"", content_type=ct, headers=hdrs,
                                  content_length=oi.size)
            if rng:
                start = oi.size - len(data) if offset < 0 else offset
                hdrs["Content-Range"] = \
                    f"bytes {start}-{start + len(data) - 1}/{oi.size}"
                return self._send(206, data, content_type=ct, headers=hdrs)
            return self._send(200, data, content_type=ct, headers=hdrs)

        def _delete_object(self, bucket, key, query):
            q1 = {k: v[0] for k, v in query.items()}
            vid = q1.get("versionId")
            if vid == "null":
                vid = ""
            versioned = srv.bucket_meta.versioning_enabled(bucket)
            res = srv.layer.delete_object(
                bucket, key, ol.ObjectOptions(version_id=vid,
                                              versioned=versioned))
            hdrs = {}
            if res.delete_marker:
                hdrs["x-amz-delete-marker"] = "true"
            if res.version_id:
                hdrs["x-amz-version-id"] = res.version_id
            self._send(204, headers=hdrs)

    return Handler


def _parse_range(spec: str) -> tuple[int, int]:
    """HTTP Range -> (offset, length) without knowing the size
    (cmd/httprange.go); negative offset = suffix, length -1 = to-end.
    Size-dependent validation/clamping happens in the object layer, so a
    ranged GET costs a single quorum metadata read."""
    m = re.match(r"^bytes=(\d*)-(\d*)$", spec.strip())
    if not m:
        raise S3Error("InvalidRange")
    first, last = m.group(1), m.group(2)
    if first == "" and last == "":
        raise S3Error("InvalidRange")
    if first == "":  # suffix range: last N bytes
        n = int(last)
        if n == 0:
            raise S3Error("InvalidRange")
        return -n, -1
    start = int(first)
    if last == "":
        return start, -1
    end = int(last)
    if end < start:
        raise S3Error("InvalidRange")
    return start, end - start + 1
