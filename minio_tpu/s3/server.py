"""S3 API HTTP server (cmd/api-router.go:82 + cmd/object-handlers.go /
cmd/bucket-handlers.go).

Path-style S3 over a threading HTTP server: the L1/L3 frontend of the
framework.  Handlers authenticate (SigV4 header or presigned), map the
route to an ObjectLayer call, and render S3 XML.  The compute-heavy body
(erasure encode/decode) happens inside the object layer on TPU.
"""

from __future__ import annotations

import datetime
import email.utils
import hashlib
import os
import re
import socket
import threading
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..iam import policy as iampol
from ..objectlayer import interface as ol
from ..objectlayer.bucket_meta import BucketMetadataSys
from . import errors as s3err
from . import sigv4

MAX_OBJECT_SIZE = 5 * 1024 * 1024 * 1024 * 1024  # 5 TiB (docs/minio-limits.md)
MAX_PUT_SIZE = 5 * 1024 * 1024 * 1024   # single PUT / part (minio-limits:28)
# bodies above this stream straight into the object layer (O(batch) RSS);
# smaller ones take the simpler buffered path
STREAM_PUT_THRESHOLD = 8 * 1024 * 1024
S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"

_BUCKET_RE = re.compile(r"^[a-z0-9][a-z0-9.\-]{1,61}[a-z0-9]$")


class _BodyReader:
    """Bounded socket-body reader with optional integrity checks: caps
    reads at the declared Content-Length, raises IncompleteBody when the
    peer hangs up early, and verifies sha256/md5 digests at EOF — the
    hash.Reader analog (pkg/hash) that lets PUTs stream while keeping
    the commit gated on body integrity."""

    def __init__(self, raw, total: int, sha256_hex: str | None = None,
                 md5_digest: bytes | None = None):
        self.raw = raw
        self.remaining = total
        self._sha = hashlib.sha256() if sha256_hex else None
        self._want_sha = sha256_hex
        self._md5 = hashlib.md5() if md5_digest else None
        self._want_md5 = md5_digest

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self.remaining
        n = min(n, self.remaining)
        if n <= 0:
            return b""
        chunks = []
        while n > 0:
            c = self.raw.read(n)
            if not c:
                raise S3Error("IncompleteBody")
            chunks.append(c)
            n -= len(c)
            self.remaining -= len(c)
        data = chunks[0] if len(chunks) == 1 else b"".join(chunks)
        if self._sha is not None:
            self._sha.update(data)
        if self._md5 is not None:
            self._md5.update(data)
        if self.remaining == 0:
            if self._sha is not None and \
                    self._sha.hexdigest() != self._want_sha:
                raise S3Error("BadDigest")
            if self._md5 is not None and \
                    self._md5.digest() != self._want_md5:
                raise S3Error("BadDigest")
        return data

    def readline(self, limit: int = 8192) -> bytes:
        """Bounded readline for aws-chunked frame headers."""
        out = bytearray()
        while len(out) < limit and self.remaining > 0:
            c = self.raw.read(1)
            if not c:
                raise S3Error("IncompleteBody")
            self.remaining -= 1
            out += c
            if out.endswith(b"\r\n"):
                break
        return bytes(out)


class _MD5Reader:
    """Content-MD5 verification over an already-decoded stream (the
    aws-chunked plain view), checked at EOF before the commit."""

    def __init__(self, inner, want_md5: bytes):
        self.inner = inner
        self._md5 = hashlib.md5()
        self._want = want_md5
        self._checked = False

    def read(self, n: int = -1) -> bytes:
        data = self.inner.read(n)
        if data:
            self._md5.update(data)
        elif not self._checked:
            self._checked = True
            if self._md5.digest() != self._want:
                raise S3Error("BadDigest")
        return data




class S3Error(Exception):
    def __init__(self, code: str):
        super().__init__(code)
        self.api = s3err.get(code)


def _http_date(ns: int) -> str:
    return email.utils.formatdate(ns / 1e9, usegmt=True)


def _iso_date(ns: int) -> str:
    return datetime.datetime.fromtimestamp(
        ns / 1e9, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


def _xml(root: ET.Element) -> bytes:
    return (b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root))


def _parse_duration(s: str) -> float:
    """'10s' / '2m' / '500ms' -> seconds (cmd/config duration keys)."""
    s = s.strip()
    try:
        if s.endswith("ms"):
            return float(s[:-2]) / 1000.0
        if s.endswith("s"):
            return float(s[:-1])
        if s.endswith("m"):
            return float(s[:-1]) * 60.0
        return float(s)
    except ValueError:
        return 10.0


def _try(fn):
    """Run a config parser, translating its ValueError into an S3Error
    (carrying the parser's .code when present)."""
    try:
        return fn()
    except ValueError as e:
        raise S3Error(getattr(e, "code", "MalformedXML")) from e


def _canned_acl_xml() -> bytes:
    """The fixed FULL_CONTROL owner ACL MinIO reports
    (cmd/bucket-handlers.go GetBucketACLHandler)."""
    root = ET.Element("AccessControlPolicy", xmlns=S3_NS)
    owner = ET.SubElement(root, "Owner")
    ET.SubElement(owner, "ID").text = "minio-tpu"
    acl = ET.SubElement(root, "AccessControlList")
    grant = ET.SubElement(acl, "Grant")
    grantee = ET.SubElement(
        grant, "Grantee",
        {"xmlns:xsi": "http://www.w3.org/2001/XMLSchema-instance",
         "xsi:type": "CanonicalUser"})
    ET.SubElement(grantee, "ID").text = "minio-tpu"
    ET.SubElement(grant, "Permission").text = "FULL_CONTROL"
    return _xml(root)


class S3Server:
    """Wires an ObjectLayer + credentials into an HTTP server."""

    def __init__(self, object_layer, access_key: str = "minioadmin",
                 secret_key: str = "minioadmin", region: str = "us-east-1",
                 host: str = "127.0.0.1", port: int = 0,
                 max_body_size: int = 1024 ** 3, iam=None):
        self.layer = object_layer
        if iam is None:
            from ..iam.sys import IAMSys
            iam = IAMSys(object_layer, access_key, secret_key)
        self.iam = iam
        self.region = region
        self.max_body_size = max_body_size
        self.bucket_meta = BucketMetadataSys(object_layer)
        from ..utils.kvconfig import Config
        self.config = Config(object_layer)
        from ..events import NotificationSys, WebhookTarget
        self.events = NotificationSys(self.bucket_meta, region=region)
        if self.config.get("notify_webhook", "enable") == "on":
            # config-driven target registration (cmd/config/notify): the
            # ARN a PUT-notification config may reference
            self.events.register_target(WebhookTarget(
                "arn:minio:sqs::1:webhook",
                self.config.get("notify_webhook", "endpoint"),
                auth_token=self.config.get("notify_webhook", "auth_token"),
                store_dir=self.config.get("notify_webhook", "queue_dir")
                or None))
        from ..events.brokers import BROKER_KINDS, target_from_config
        for kind in BROKER_KINDS:
            t = target_from_config(kind, self.config)
            if t is not None:
                self.events.register_target(t)
        # wired in by server_main / tests when those subsystems are enabled
        self.replication = None  # ReplicationSys (minio_tpu/background)
        self.usage = None        # data-usage cache (crawler)
        self.healer = None       # BackgroundHealer sweep
        self.mrf = None          # MRFQueue
        self.tracker = None      # DataUpdateTracker (crawler bloom filter)
        from ..crypto.kms import LocalKMS
        self.kms = LocalKMS.from_env_or_store(object_layer)
        from ..iam.openid import OpenIDProvider
        self.openid = OpenIDProvider.from_config(self.config)
        from ..iam.ldap import LDAPConfig, LDAPIdentity
        _lcfg = LDAPConfig.from_config(self.config)
        self.ldap = LDAPIdentity(_lcfg) if _lcfg.enabled else None
        # ILM tiering (cmd/bucket-lifecycle.go transitionObject): tier
        # registry persisted in the system volume
        from ..objectlayer.tiering import TransitionSys
        from ..storage.xl_storage import SYS_DIR
        blobs, _ = object_layer._fanout(
            lambda d: d.read_all(SYS_DIR, "tiers/tiers.json"))
        blob = next((b for b in blobs if b), None)
        self.transition = TransitionSys.from_json(object_layer, blob) \
            if blob else TransitionSys(object_layer)
        # observability (cmd/http-tracer.go, cmd/logger/audit.go):
        # trace hub is process-global (mirrors globalHTTPTrace); audit
        # log is per-server so deployments keep entries separate
        from ..obs import audit as _obs_audit
        from ..obs import logger as _obs_logger
        from ..obs import trace as _obs_trace
        self.trace_hub = _obs_trace.HTTP_TRACE
        self.audit = _obs_audit.AuditLog()
        self.logger = _obs_logger.GLOBAL
        self.node_name = f"{host}:{port}"
        if self.config.get("audit_webhook", "enable") == "on":
            self.audit.targets.append(_obs_logger.HTTPLogTarget(
                self.config.get("audit_webhook", "endpoint"),
                self.config.get("audit_webhook", "auth_token")))
        if self.config.get("logger_webhook", "enable") == "on":
            self.logger.targets.append(_obs_logger.HTTPLogTarget(
                self.config.get("logger_webhook", "endpoint"),
                self.config.get("logger_webhook", "auth_token")))
        if self.config.get("compression", "enable") == "on":
            # build/load the native codec BEFORE serving so the first
            # request never blocks on a compile, and say which engine runs
            from .. import compress as mtc
            import logging
            if not mtc.native_available():
                logging.getLogger("minio_tpu").warning(
                    "native snappy codec unavailable; using the pure-"
                    "Python fallback (slow)")
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        # federation binds the *actual* port (ephemeral binds resolve
        # only once the listener exists)
        from ..utils.fed_dns import FederationSys
        self.federation = FederationSys.from_config(
            self.config, host or "127.0.0.1", self.port)
        self._thread: threading.Thread | None = None
        # set by admin service?action=stop so a node-mode main thread
        # parked on it can finish shutdown (RPC plane + process exit)
        self.shutdown = threading.Event()
        # peer control-plane notifier (cluster mode; parallel/peer.py)
        self.peers = None
        # request admission throttle (cmd/handler-api.go:29-40
        # requestsPool/requestsDeadline; config keys cmd/config/api):
        # bounds concurrent S3 requests; excess waits up to the deadline
        # then gets 503 SlowDown instead of piling up threads
        try:
            req_max = int(self.config.get("api", "requests_max") or 0)
        except ValueError:
            req_max = 0
        if req_max <= 0:
            req_max = 16 * (os.cpu_count() or 8)   # auto sizing
        self.requests_deadline_s = _parse_duration(
            self.config.get("api", "requests_deadline") or "10s")
        self._req_sem = threading.BoundedSemaphore(req_max)

    def attach_tracker(self, tracker) -> None:
        """Wire the data-update tracker into event marking AND listing-
        cache validity (the metacache consults it instead of waiting
        out its TTL — cmd/metacache-bucket.go coupling)."""
        self.tracker = tracker
        from ..objectlayer.metacache import managers_of
        for mc in managers_of(self.layer):
            mc.tracker = tracker

    def attach_peers(self, notifier) -> None:
        """Wire the peer fan-out: IAM/bucket-metadata mutations reload on
        every node immediately (cmd/peer-rest-common.go:27-61), and the
        trace hub keeps a pollable ring for cross-node aggregation."""
        self.peers = notifier
        self.bucket_meta.on_change = notifier.bucket_meta_changed
        self.iam.on_change = notifier.iam_changed
        self.trace_hub.enable_ring()

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopping = True          # health probes report offline
        self.httpd.shutdown()
        self.httpd.server_close()
        self.events.close()
        if self.peers is not None:
            self.peers.close()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def notify(self, event_name: str, bucket: str, oi,
               req_params: dict | None = None) -> None:
        """Fire a bucket event into the notification system."""
        if self.tracker is not None and oi is not None:
            # feed the crawler's change bloom filter on every mutation
            self.tracker.mark(bucket, getattr(oi, "name", ""))
        if self.peers is not None and oi is not None:
            # feed every PEER's tracker too: their cached listings for
            # this bucket go stale now, not after the metacache TTL
            self.peers.object_changed(bucket, getattr(oi, "name", ""))
        self.events.send(event_name, bucket, oi, req_params or {})

    def replicate(self, bucket: str, oi, delete: bool = False) -> None:
        """Queue async replication if the bucket's config asks for it
        (no-op until ReplicationSys is attached)."""
        if self.replication is not None:
            self.replication.queue(bucket, oi, delete=delete)


def _layer_set_drive_count(layer) -> int:
    """Drives per erasure set for any topology shape (storage-class
    parity is bounded by the SET size, not total drives)."""
    n = getattr(layer, "set_drive_count", 0)
    if n:
        return n
    pools = getattr(layer, "pools", None)
    if pools:
        return getattr(pools[0], "set_drive_count", 0)
    return len(getattr(layer, "disks", []) or [])


def _api_name(method: str, bucket: str, key: str, q1: dict) -> str:
    """Best-effort S3 API name for traces/audit (the reference names come
    from mux route registration, cmd/api-router.go)."""
    if bucket == "minio-tpu" or not bucket:
        if method == "POST" and not bucket:
            return "STS"
        return "AdminAPI" if bucket else "ListBuckets"
    sub = {"uploads": "MultipartUpload", "uploadId": "MultipartUpload",
           "tagging": "Tagging", "retention": "Retention",
           "legal-hold": "LegalHold", "select": "SelectObjectContent",
           "versioning": "Versioning", "policy": "BucketPolicy",
           "lifecycle": "BucketLifecycle", "encryption": "BucketEncryption",
           "replication": "BucketReplication", "notification":
           "BucketNotification", "object-lock": "ObjectLockConfig",
           "versions": "ListObjectVersions", "delete": "DeleteObjects"}
    feature = next((v for k, v in sub.items() if k in q1), "")
    if key:
        base = {"GET": "GetObject", "HEAD": "HeadObject",
                "PUT": "PutObject", "DELETE": "DeleteObject",
                "POST": "PostObject"}.get(method, method)
        if feature and feature != "MultipartUpload":
            return {"GET": "Get", "PUT": "Put",
                    "DELETE": "Delete"}.get(method, "") + feature \
                if feature in ("Tagging", "Retention", "LegalHold") \
                else feature
        if feature == "MultipartUpload":
            return {"POST": "CompleteMultipartUpload"
                    if "uploadId" in q1 else "CreateMultipartUpload",
                    "PUT": "UploadPart", "GET": "ListParts",
                    "DELETE": "AbortMultipartUpload"}.get(method, base)
        return base
    base = {"GET": "ListObjectsV2" if q1.get("list-type") == "2"
            else "ListObjectsV1",
            "HEAD": "HeadBucket", "PUT": "MakeBucket",
            "DELETE": "DeleteBucket", "POST": "PostPolicyBucket"}
    if feature:
        return ({"GET": "Get", "PUT": "Put", "DELETE": "Delete"}
                .get(method, "") + feature) \
            if feature.startswith("Bucket") or feature == "Versioning" \
            else feature
    return base.get(method, method)


def _make_handler(srv: S3Server):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "MinioTPU"

        # -- plumbing ------------------------------------------------------

        def log_message(self, fmt, *args):  # quiet; tracing hooks later
            pass

        def _split(self):
            u = urllib.parse.urlsplit(self.path)
            path = urllib.parse.unquote(u.path)
            query = urllib.parse.parse_qs(u.query, keep_blank_values=True)
            parts = path.lstrip("/").split("/", 1)
            bucket = parts[0]
            key = parts[1] if len(parts) > 1 else ""
            return path, bucket, key, query

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length") or 0)
            if n > srv.max_body_size:
                # reject before buffering: unauthenticated clients must not
                # be able to force huge allocations
                raise S3Error("EntityTooLarge")
            return self.rfile.read(n) if n else b""

        def _auth(self, path, query, payload: bytes) -> bytes:
            self._query_token = query.get("X-Amz-Security-Token", [""])[0]
            out = self._auth_inner(path, query, payload)
            self._check_session_token()
            return out

        def _auth_inner(self, path, query, payload: bytes) -> bytes:
            """Authenticate; returns the effective payload (aws-chunked
            bodies are signature-verified per chunk and de-framed).  Sets
            self.access_key for authorization."""
            lookup = srv.iam.lookup_secret
            hdrs = {k: v for k, v in self.headers.items()}
            try:
                if "Authorization" not in hdrs and \
                        "X-Amz-Signature" not in query and \
                        not ("Signature" in query and
                             "AWSAccessKeyId" in query):
                    # anonymous request: authorization happens against the
                    # bucket policy alone (cmd/auth-handler.go authTypeAnonymous)
                    self.access_key = ""
                    sha = self.headers.get("x-amz-content-sha256")
                    if sha and sha != sigv4.UNSIGNED_PAYLOAD:
                        if hashlib.sha256(payload).hexdigest() != sha:
                            raise S3Error("BadDigest")
                    return payload
                auth_hdr = hdrs.get("Authorization", "")
                if auth_hdr.startswith("AWS "):
                    # Signature V2 header auth (cmd/signature-v2.go)
                    from . import sigv2
                    self.access_key = sigv2.verify_request(
                        lookup, self.command, path, query, hdrs)
                    return payload
                if "Signature" in query and "AWSAccessKeyId" in query:
                    # presigned V2
                    from . import sigv2
                    self.access_key = sigv2.verify_presigned(
                        lookup, self.command, path, query, hdrs)
                    return payload
                if "X-Amz-Signature" in query:
                    self.access_key = sigv4.verify_presigned(
                        lookup, self.command, path, query, hdrs,
                        region=srv.region)
                    return payload
                sha = self.headers.get("x-amz-content-sha256",
                                       sigv4.UNSIGNED_PAYLOAD)
                if sha == sigv4.STREAMING_PAYLOAD:
                    self.access_key, key, seed, amz_date, scope = \
                        sigv4.verify_request_streaming(
                            lookup, self.command, path, query, hdrs,
                            region=srv.region)
                    return sigv4.decode_chunked_payload(
                        payload, key, seed, amz_date, scope)
                if sha != sigv4.UNSIGNED_PAYLOAD:
                    got = hashlib.sha256(payload).hexdigest()
                    if got != sha:
                        raise S3Error("BadDigest")
                self.access_key = sigv4.verify_request(
                    lookup, self.command, path, query, hdrs, sha,
                    region=srv.region)
                return payload
            except sigv4.SigV4Error as e:
                raise S3Error(e.code) from e

        def _allow(self, action: str, resource: str = "") -> None:
            """Authorize the authenticated key for an S3 action: bucket
            policy first (explicit Deny wins, Allow grants even anonymous),
            then IAM (checkRequestAuthType -> IAMSys.IsAllowed)."""
            bucket = resource.split("/", 1)[0]
            # bucket policy can only speak for s3: actions — admin:* must
            # never be grantable by a bucket document
            if bucket and action.startswith("s3:"):
                try:
                    pol = srv.bucket_meta.get_bucket_policy(bucket)
                    verdict = pol.is_allowed(
                        self.access_key, action, resource) \
                        if pol is not None else None
                except Exception as e:  # noqa: BLE001 — fail CLOSED: an
                    # unevaluable policy must not silently drop its Denies
                    raise S3Error("AccessDenied") from e
                if verdict is False:
                    raise S3Error("AccessDenied")
                if verdict is True:
                    # a bucket-policy Allow still intersects with an STS
                    # session policy — temp creds never exceed their bound
                    if srv.iam.session_policy_allows(self.access_key,
                                                     action, resource):
                        return
                    raise S3Error("AccessDenied")
            if not self.access_key or \
                    not srv.iam.is_allowed(self.access_key, action,
                                           resource):
                raise S3Error("AccessDenied")

        def _send_prologue(self, status: int, sent_bytes: int,
                           entity_len: int, content_type: str,
                           headers: dict | None):
            """Shared response plumbing (metrics, trace bookkeeping,
            status line + common headers) for _send and _send_stream.
            sent_bytes feeds metrics (0 for HEAD); entity_len is the
            Content-Length header value."""
            from ..admin.metrics import GLOBAL as mtr
            mtr.inc("mt_s3_requests_total",
                    {"method": self.command, "status": str(status)})
            mtr.inc("mt_s3_tx_bytes_total", value=sent_bytes)
            self._resp_status = status
            self._resp_headers = dict(headers or {})
            self._resp_bytes = getattr(self, "_resp_bytes", 0) + sent_bytes
            if not getattr(self, "_ttfb_ns", 0) and \
                    getattr(self, "_t0_ns", 0):
                import time as _time
                self._ttfb_ns = _time.time_ns() - self._t0_ns
            self.send_response(status)
            self.send_header("x-amz-request-id",
                             getattr(self, "_req_id", None)
                             or uuid.uuid4().hex[:16])
            self.send_header("Server", "MinioTPU")
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(entity_len))
            self.end_headers()

        def _send(self, status: int, body: bytes = b"",
                  content_type: str = "application/xml",
                  headers: dict | None = None,
                  content_length: int | None = None):
            """content_length: explicit value for HEAD responses (body is
            not sent but the header must describe the entity)."""
            self._send_prologue(
                status, len(body),
                len(body) if content_length is None else content_length,
                content_type, headers)
            if body and self.command != "HEAD":
                self.wfile.write(body)

        def _send_stream(self, status: int, gen, total: int,
                         content_type: str, headers: dict | None = None):
            """Stream a known-length body chunk by chunk (the
            NewGetObjectReader pipeline end, cmd/object-api-utils.go:586).
            On a mid-stream failure the connection is dropped — the
            short body vs Content-Length signals truncation."""
            # pull the first chunk BEFORE committing the status line so
            # an immediately-failing read still yields a proper XML error
            it = iter(gen)
            first = b""
            if self.command != "HEAD" and total:
                try:
                    first = next(it)
                except StopIteration:
                    first = b""
            self._send_prologue(status, total, total, content_type,
                                headers)
            try:
                if first:
                    self.wfile.write(first)
                for chunk in it:
                    if chunk:
                        self.wfile.write(chunk)
            except Exception:   # noqa: BLE001 — headers are gone; a
                # second response would corrupt the stream
                self.close_connection = True

        def _fail(self, e: Exception, resource: str = ""):
            from ..crypto.sse import SSEError
            from ..parallel.dsync import LockLost, LockTimeout
            if isinstance(e, S3Error):
                api = e.api
            elif isinstance(e, (SSEError, sigv4.SigV4Error)):
                api = s3err.get(e.code)
            elif isinstance(e, ol.ObjectLayerError):
                api = s3err.from_object_error(e)
            elif isinstance(e, (LockTimeout, LockLost)):
                # lock contention is congestion, not a server fault
                # (the reference maps operation timeouts to 503)
                api = s3err.get("SlowDown")
            else:
                api = s3err.get("InternalError")
            self._send(api.http_status, s3err.to_xml(api, resource))

        def _dispatch(self):
            """Trace/audit wrapper around the real dispatcher
            (cmd/http-tracer.go httpTraceAll + cmd/logger/audit.go)."""
            from ..obs import trace as _trace
            self._t0_ns = _trace.now_ns()
            self._req_id = uuid.uuid4().hex[:16]
            self._resp_status = 0
            self._resp_headers = {}
            self._resp_bytes = 0
            self._ttfb_ns = 0
            self._rx_bytes = 0
            # request-pool admission (cmd/handler-api.go:29 maxClients):
            # S3 traffic only — admin/metrics/health stay reachable when
            # the data plane is saturated
            throttled = not urllib.parse.urlsplit(self.path).path \
                .startswith("/minio-tpu/")
            # capture the pool object: admin SetConfigKV can swap
            # srv._req_sem mid-flight, and acquire/release must pair on
            # the same semaphore
            sem = srv._req_sem if throttled else None
            if sem is not None and not sem.acquire(
                    timeout=srv.requests_deadline_s):
                try:
                    self._fail(S3Error("SlowDown"))
                finally:
                    self.close_connection = True
                    try:    # 503s must show up in trace/audit streams
                        self._record_request()
                    except Exception:  # noqa: BLE001
                        pass
                return
            try:
                self._dispatch_inner()
            finally:
                if sem is not None:
                    sem.release()
                try:
                    self._record_request()
                except Exception:   # noqa: BLE001 — never fail a request
                    pass            # on account of observability

        def _record_request(self):
            from ..obs import trace as _trace
            dur = _trace.now_ns() - self._t0_ns
            path, bucket, key, query = self._split()
            q1 = {k: v[0] for k, v in query.items()}
            api_name = _api_name(self.command, bucket, key, q1)
            if srv.trace_hub.num_subscribers > 0 or \
                    srv.trace_hub.ring_active:
                srv.trace_hub.publish(_trace.make_trace(
                    srv.node_name, api_name,
                    method=self.command, path=path,
                    raw_query="&".join(f"{k}={v}" for k, v in q1.items()),
                    client=self.client_address[0],
                    req_headers=dict(self.headers.items()),
                    status_code=self._resp_status,
                    resp_headers=self._resp_headers,
                    input_bytes=self._rx_bytes,
                    output_bytes=self._resp_bytes,
                    start_ns=self._t0_ns, ttfb_ns=self._ttfb_ns,
                    duration_ns=dur))
            if srv.audit.targets or srv.audit.recent is not None:
                srv.audit.publish(srv.audit.entry(
                    api_name=api_name, bucket=bucket, obj=key,
                    status_code=self._resp_status, rx=self._rx_bytes,
                    tx=self._resp_bytes, duration_ns=dur,
                    remote_host=self.client_address[0],
                    request_id=self._req_id,
                    user_agent=self.headers.get("User-Agent", ""),
                    access_key=getattr(self, "access_key", ""),
                    query=q1,
                    req_headers=dict(self.headers.items()),
                    resp_headers=self._resp_headers))

        def _dispatch_inner(self):
            path, bucket, key, query = self._split()
            from ..admin import handlers as admin_handlers
            from ..admin.metrics import GLOBAL as mtr
            try:
                if path.startswith("/minio-tpu/health/"):
                    # healthcheck router (cmd/healthcheck-router.go:40):
                    # unauthenticated, throttle-exempt — k8s probes must
                    # reach it when the server is saturated or keyless
                    self._body()
                    return self._health_api(path, query)
                if path == admin_handlers.METRICS_PATH:
                    self._body()  # drain keep-alive body before replying
                    if self.command != "GET":
                        raise S3Error("MethodNotAllowed")
                    return admin_handlers.handle(self, srv, path, query, b"")
                from . import web as web_handlers
                if path == web_handlers.WEBRPC_PATH or \
                        path == web_handlers.ZIP_PATH or \
                        path.startswith((web_handlers.BROWSER_PATH,
                                         web_handlers.UPLOAD_PREFIX,
                                         web_handlers.DOWNLOAD_PREFIX)):
                    # web endpoints authenticate with their own JWT
                    if web_handlers.handle(self, srv, path, query,
                                           self._body):
                        return
                # browser redirect (cmd/generic-handlers.go
                # setBrowserRedirectHandler): an unauthenticated GET /
                # from a web browser lands on the UI, S3 clients (signed
                # or anonymous API calls) are never redirected
                if path == "/" and self.command == "GET" and \
                        "Mozilla" in self.headers.get("User-Agent", "") \
                        and "Authorization" not in self.headers and \
                        "X-Amz-Credential" not in (query or {}) and \
                        "AWSAccessKeyId" not in (query or {}):
                    self._body()
                    self.send_response(303)
                    self.send_header("Location", web_handlers.BROWSER_PATH)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if self._try_stream_put(path, bucket, key, query):
                    return
                payload = self._body()
                self._rx_bytes = len(payload)
                mtr.inc("mt_s3_rx_bytes_total", value=len(payload))
                payload = self._auth(path, query, payload)
                if path.startswith("/minio-tpu/"):
                    if admin_handlers.handle(self, srv, path, query,
                                             payload):
                        return
                if bucket == "minio-tpu":
                    # reserved namespace (isMinioReservedBucket analog):
                    # admin/metrics own this prefix; never an S3 bucket
                    raise S3Error("AccessDenied")
                if not bucket:
                    if self.command == "POST":
                        return self._sts_api(payload)
                    return self._list_buckets()
                if not _BUCKET_RE.match(bucket):
                    raise S3Error("InvalidBucketName")
                try:
                    if key:
                        return self._object_api(bucket, key, query,
                                                payload)
                    return self._bucket_api(bucket, query, payload)
                except ol.BucketNotFound:
                    # federated bucket homed on another cluster: 307 to
                    # its owner (cmd/handler-utils.go redirect path)
                    if srv.federation is not None:
                        rec = srv.federation.lookup_other(bucket)
                        if rec is not None:
                            u = urllib.parse.urlsplit(self.path)
                            loc = (f"http://{rec.host}:{rec.port}"
                                   f"{u.path}"
                                   + (f"?{u.query}" if u.query else ""))
                            return self._send(
                                307, b"", headers={"Location": loc})
                    raise
            except Exception as e:  # noqa: BLE001 — every error becomes XML
                self._fail(e, path)

        do_GET = do_PUT = do_HEAD = do_DELETE = do_POST = \
            lambda self: self._dispatch()

        # -- STS (cmd/sts-handlers.go) -------------------------------------

        STS_NS = "https://sts.amazonaws.com/doc/2011-06-15/"

        def _sts_fail(self, code: str, msg: str = ""):
            root = ET.Element("ErrorResponse", xmlns=self.STS_NS)
            err = ET.SubElement(root, "Error")
            ET.SubElement(err, "Type").text = "Sender"
            ET.SubElement(err, "Code").text = code
            ET.SubElement(err, "Message").text = msg or code
            status = 403 if code in ("AccessDenied", "ExpiredToken") \
                else 400
            self._send(status, _xml(root))

        def _sts_api(self, payload: bytes):
            from ..iam import sts as _sts
            form = {k: v[0] for k, v in urllib.parse.parse_qs(
                payload.decode("utf-8", "replace"),
                keep_blank_values=True).items()}
            action = form.get("Action", "")
            if action in ("AssumeRoleWithWebIdentity",
                          "AssumeRoleWithClientGrants"):
                return self._sts_web_identity(form, action)
            if action == "AssumeRoleWithLDAPIdentity":
                return self._sts_ldap_identity(form)
            if action != "AssumeRole":
                return self._sts_fail("InvalidAction", action)
            if not self.access_key:
                return self._sts_fail("AccessDenied",
                                      "request must be signed")
            try:
                duration = int(form.get("DurationSeconds",
                                        str(_sts.DEFAULT_DURATION_S)))
            except ValueError:
                return self._sts_fail("InvalidParameterValue",
                                      "DurationSeconds")
            policy = form.get("Policy") or None
            try:
                creds = srv.iam.assume_role(self.access_key, duration,
                                            policy)
            except _sts.STSError as e:
                return self._sts_fail(e.code, str(e))
            root = ET.Element("AssumeRoleResponse", xmlns=self.STS_NS)
            result = ET.SubElement(root, "AssumeRoleResult")
            ce = ET.SubElement(result, "Credentials")
            ET.SubElement(ce, "AccessKeyId").text = creds.access_key
            ET.SubElement(ce, "SecretAccessKey").text = creds.secret_key
            ET.SubElement(ce, "SessionToken").text = creds.session_token
            ET.SubElement(ce, "Expiration").text = \
                datetime.datetime.fromtimestamp(
                    creds.expiration, datetime.timezone.utc).strftime(
                        "%Y-%m-%dT%H:%M:%SZ")
            meta = ET.SubElement(root, "ResponseMetadata")
            ET.SubElement(meta, "RequestId").text = uuid.uuid4().hex[:16]
            self._send(200, _xml(root))

        def _sts_ldap_identity(self, form: dict):
            """AssumeRoleWithLDAPIdentity (cmd/sts-handlers.go:436):
            verify the username/password against the configured
            directory, mint temp creds carrying the LDAP-mapped
            policies.  Unsigned by design — the password is the
            credential."""
            from ..iam import ldap as _ldap
            from ..iam import sts as _sts
            if srv.ldap is None or not srv.ldap.config.enabled:
                return self._sts_fail(
                    "NotImplemented",
                    "no LDAP provider configured (identity_ldap)")
            username = form.get("LDAPUsername", "")
            password = form.get("LDAPPassword", "")
            if not username or not password:
                return self._sts_fail(
                    "MissingParameter",
                    "LDAPUsername and LDAPPassword cannot be empty")
            policy = form.get("Policy") or None
            if policy and len(policy) > 2048:
                return self._sts_fail(
                    "InvalidParameterValue",
                    "session policy exceeds 2048 characters")
            try:
                duration = int(form.get(
                    "DurationSeconds", str(srv.ldap.config.sts_expiry_s)))
            except ValueError:
                return self._sts_fail("InvalidParameterValue",
                                      "DurationSeconds")
            try:
                user_dn, groups = srv.ldap.bind(username, password)
            except _ldap.LDAPError as e:
                return self._sts_fail("InvalidParameterValue",
                                      f"LDAP server error: {e}")
            try:
                creds = srv.iam.assume_role_ldap_identity(
                    user_dn, username, groups, duration,
                    session_policy=policy)
            except _sts.STSError as e:
                return self._sts_fail(e.code, str(e))
            except Exception as e:  # noqa: BLE001 — surface as STS error
                return self._sts_fail("InvalidParameterValue", str(e))
            root = ET.Element("AssumeRoleWithLDAPIdentityResponse",
                              xmlns=self.STS_NS)
            result = ET.SubElement(
                root, "AssumeRoleWithLDAPIdentityResult")
            ce = ET.SubElement(result, "Credentials")
            ET.SubElement(ce, "AccessKeyId").text = creds.access_key
            ET.SubElement(ce, "SecretAccessKey").text = creds.secret_key
            ET.SubElement(ce, "SessionToken").text = creds.session_token
            ET.SubElement(ce, "Expiration").text = \
                datetime.datetime.fromtimestamp(
                    creds.expiration, datetime.timezone.utc).strftime(
                        "%Y-%m-%dT%H:%M:%SZ")
            meta = ET.SubElement(root, "ResponseMetadata")
            ET.SubElement(meta, "RequestId").text = uuid.uuid4().hex[:16]
            self._send(200, _xml(root))

        def _sts_web_identity(self, form: dict, action: str):
            """AssumeRoleWithWebIdentity (cmd/sts-handlers.go): validate
            the provider-issued JWT, map the policy claim, mint creds.
            Unsigned by design — the JWT is the credential."""
            from ..iam import openid as _oidc
            from ..iam import sts as _sts
            if srv.openid is None:
                return self._sts_fail(
                    "NotImplemented",
                    "no OpenID provider configured (identity_openid)")
            token = form.get("WebIdentityToken") or form.get("Token", "")
            if not token:
                return self._sts_fail("InvalidParameterValue",
                                      "WebIdentityToken required")
            try:
                duration = int(form.get("DurationSeconds",
                                        str(_sts.DEFAULT_DURATION_S)))
            except ValueError:
                return self._sts_fail("InvalidParameterValue",
                                      "DurationSeconds")
            try:
                claims = srv.openid.authenticate(token)
            except _oidc.OpenIDError as e:
                return self._sts_fail("AccessDenied", str(e))
            policies = srv.openid.policies_of(claims)
            if not policies:
                return self._sts_fail(
                    "AccessDenied",
                    f"token carries no {srv.openid.claim_name!r} claim")
            from ..iam.sys import NoSuchPolicy
            try:
                creds = srv.iam.assume_role_web_identity(
                    claims["sub"], policies, duration)
            except NoSuchPolicy as e:
                return self._sts_fail("AccessDenied",
                                      f"unknown policy: {e}")
            except _sts.STSError as e:
                return self._sts_fail(e.code, str(e))
            root = ET.Element(f"{action}Response", xmlns=self.STS_NS)
            result = ET.SubElement(root, f"{action}Result")
            ce = ET.SubElement(result, "Credentials")
            ET.SubElement(ce, "AccessKeyId").text = creds.access_key
            ET.SubElement(ce, "SecretAccessKey").text = creds.secret_key
            ET.SubElement(ce, "SessionToken").text = creds.session_token
            ET.SubElement(ce, "Expiration").text = \
                datetime.datetime.fromtimestamp(
                    creds.expiration, datetime.timezone.utc).strftime(
                        "%Y-%m-%dT%H:%M:%SZ")
            ET.SubElement(result, "SubjectFromWebIdentityToken").text = \
                claims["sub"]
            meta = ET.SubElement(root, "ResponseMetadata")
            ET.SubElement(meta, "RequestId").text = uuid.uuid4().hex[:16]
            self._send(200, _xml(root))

        def _check_session_token(self):
            """Temp credentials must present their session token on every
            request (checkClaimsFromToken, cmd/auth-handler.go)."""
            from ..iam import sts as _sts
            if not self.access_key:
                return
            try:
                u = srv.iam.get_user(self.access_key)
            except Exception:  # noqa: BLE001 — root or unknown: no claims
                return
            if not (u.parent_user and u.expiration):
                return
            tok = self.headers.get("x-amz-security-token", "") or \
                self._query_token
            if not tok:
                raise S3Error("AccessDenied")
            try:
                claims = _sts.verify_token(tok, srv.iam.root.secret_key)
            except _sts.STSError as e:
                raise S3Error("ExpiredToken" if e.code == "ExpiredToken"
                              else "AccessDenied") from e
            if claims.get("accessKey") != self.access_key:
                raise S3Error("AccessDenied")

        # -- healthcheck router (cmd/healthcheck-router.go:40) ------------

        def _health_api(self, path, query):
            if self.command not in ("GET", "HEAD"):
                raise S3Error("MethodNotAllowed")
            leaf = path[len("/minio-tpu/health/"):]
            status = 200
            headers = {}
            if leaf == "cluster":
                # readiness for traffic incl. maintenance pre-check
                # (cmd/healthcheck-handler.go:28-66 ClusterCheckHandler)
                maint = (query or {}).get("maintenance",
                                          [""])[0] == "true"
                h = srv.layer.health(maintenance=maint)
                if h["write_quorum"]:
                    headers["X-Minio-Write-Quorum"] = \
                        str(h["write_quorum"])
                if not h["healthy"]:
                    if h["healing_drives"]:
                        headers["X-Minio-Healing-Drives"] = \
                            str(h["healing_drives"])
                    # maintenance probe: 412 tells the orchestrator the
                    # node can NOT be safely taken down
                    status = 412 if maint else 503
            elif leaf in ("live", "ready"):
                # process-level probes: always 200 while the process
                # serves, exactly like the reference
                # (cmd/healthcheck-handler.go:69-84 returns success
                # unconditionally); a stopping server only annotates
                # the informational offline header
                if getattr(srv, "_stopping", False):
                    headers["X-Minio-Server-Status"] = "offline"
            else:
                raise S3Error("NoSuchKey")
            self._send(status, b"", headers=headers)

        # -- service / bucket APIs ----------------------------------------

        def _list_buckets(self):
            if self.command != "GET":
                raise S3Error("MethodNotAllowed")
            self._allow(iampol.LIST_ALL_MY_BUCKETS)
            root = ET.Element("ListAllMyBucketsResult", xmlns=S3_NS)
            owner = ET.SubElement(root, "Owner")
            ET.SubElement(owner, "ID").text = "minio-tpu"
            ET.SubElement(owner, "DisplayName").text = "minio-tpu"
            buckets = ET.SubElement(root, "Buckets")
            for b in srv.layer.list_buckets():
                be = ET.SubElement(buckets, "Bucket")
                ET.SubElement(be, "Name").text = b.name
                ET.SubElement(be, "CreationDate").text = _iso_date(b.created)
            self._send(200, _xml(root))

        # config subresources: query-param -> (module handler); each stores
        # the raw document in BucketMetadataSys and round-trips it on GET
        # (cmd/bucket-handlers.go, cmd/bucket-lifecycle-handlers.go, ...)

        def _config_api(self, bucket, query, payload) -> bool:
            from ..bucket import (encryption, lifecycle, notification,
                                  objectlock, replication, tags)
            from ..bucket import policy as bpolicy
            cmd = self.command
            if not ({"policy", "lifecycle", "encryption", "replication",
                     "notification", "object-lock", "tagging", "quota",
                     "acl", "cors", "website", "accelerate",
                     "requestPayment", "logging"} & set(query)):
                return False

            def exists():
                # authorization happens BEFORE the existence check so an
                # unauthenticated caller cannot enumerate bucket names by
                # distinguishing 404 from 403 (cmd/auth-handler.go order)
                srv.layer.get_bucket_info(bucket)

            def crud(param, get_act, put_act, parse, not_found,
                     store_key=None, deletable=True, parse_err="MalformedXML"):
                if param not in query:
                    return False
                store_key = store_key or param
                if cmd == "PUT":
                    self._allow(put_act, bucket)
                    exists()
                    try:
                        doc = parse(payload)
                    except (ValueError, KeyError) as e:
                        code = getattr(e, "code", parse_err)
                        raise S3Error(code) from e
                    srv.bucket_meta.set_config(bucket, store_key, doc)
                    self._send(200)
                elif cmd == "GET":
                    self._allow(get_act, bucket)
                    exists()
                    raw = srv.bucket_meta.get_config(bucket, store_key)
                    if raw is None:
                        raise S3Error(not_found)
                    ctype = "application/json" \
                        if store_key == "policy" else "application/xml"
                    self._send(200, raw.encode(), content_type=ctype)
                elif cmd == "DELETE" and deletable:
                    self._allow(put_act, bucket)
                    exists()
                    srv.bucket_meta.set_config(bucket, store_key, None)
                    self._send(204)
                else:
                    raise S3Error("MethodNotAllowed")
                return True

            # dummy sub-resources (cmd/dummy-handlers.go): authorize with
            # the bucket-policy action, validate existence, then return
            # the fixed default (or the documented error); DELETE website
            # succeeds as a no-op
            _DUMMY = {
                "accelerate": (
                    b'<?xml version="1.0" encoding="UTF-8"?>'
                    b'<AccelerateConfiguration xmlns="http://s3.amazonaws'
                    b'.com/doc/2006-03-01/"/>'),
                "requestPayment": (
                    b'<?xml version="1.0" encoding="UTF-8"?>'
                    b'<RequestPaymentConfiguration xmlns="http://s3.'
                    b'amazonaws.com/doc/2006-03-01/"><Payer>BucketOwner'
                    b'</Payer></RequestPaymentConfiguration>'),
                "logging": (
                    b'<?xml version="1.0" encoding="UTF-8"?>'
                    b'<BucketLoggingStatus xmlns="http://s3.amazonaws.com'
                    b'/doc/2006-03-01/"></BucketLoggingStatus>'),
                "website": None,     # GET -> NoSuchWebsiteConfiguration
            }
            for param, body in _DUMMY.items():
                if param not in query:
                    continue
                self._allow(iampol.GET_BUCKET_POLICY, bucket)
                exists()
                if param == "website" and cmd == "DELETE":
                    self._send(204)
                elif cmd == "GET":
                    if body is None:
                        raise S3Error("NoSuchWebsiteConfiguration")
                    self._send(200, body,
                               content_type="application/xml")
                else:
                    raise S3Error("NotImplemented")
                return True

            if crud("policy", iampol.GET_BUCKET_POLICY,
                    iampol.PUT_BUCKET_POLICY,
                    lambda p: bpolicy.BucketPolicy.parse(p, bucket)
                    .to_json().decode(),
                    "NoSuchBucketPolicy", parse_err="MalformedPolicy"):
                return True
            if crud("lifecycle", iampol.GET_LIFECYCLE, iampol.PUT_LIFECYCLE,
                    lambda p: lifecycle.Lifecycle.parse(p).to_xml().decode(),
                    "NoSuchLifecycleConfiguration"):
                return True
            if crud("encryption", iampol.GET_BUCKET_ENCRYPTION,
                    iampol.PUT_BUCKET_ENCRYPTION,
                    lambda p: encryption.SSEConfig.parse(p)
                    .to_xml().decode(),
                    "ServerSideEncryptionConfigurationNotFoundError"):
                return True
            if "replication" in query and cmd == "PUT":
                # destination ARN must name a registered remote target
                self._allow(iampol.PUT_REPLICATION, bucket)
                exists()
                cfg = _try(lambda: replication.Config.parse(payload))
                if not srv.bucket_meta.versioning_enabled(bucket):
                    raise S3Error("InvalidRequest")
                if srv.replication is not None:
                    for r in cfg.rules:
                        if not srv.replication.arn_exists(
                                r.destination_arn):
                            raise S3Error(
                                "ReplicationDestinationNotFoundError")
                srv.bucket_meta.set_config(bucket, "replication",
                                           cfg.to_xml().decode())
                return self._send(200) or True
            if crud("replication", iampol.GET_REPLICATION,
                    iampol.PUT_REPLICATION,
                    lambda p: replication.Config.parse(p).to_xml().decode(),
                    "ReplicationConfigurationNotFoundError"):
                return True
            if "notification" in query:
                if cmd == "PUT":
                    self._allow(iampol.PUT_BUCKET_NOTIFICATION, bucket)
                    exists()
                    cfg = _try(lambda: notification.Config.parse(
                        payload, valid_arns=srv.events.valid_arns()))
                    srv.bucket_meta.set_config(
                        bucket, "notification",
                        cfg.to_xml().decode() if cfg.targets else None)
                    return self._send(200) or True
                if cmd == "GET":
                    self._allow(iampol.GET_BUCKET_NOTIFICATION, bucket)
                    exists()
                    raw = srv.bucket_meta.get_config(bucket, "notification")
                    if raw is None:
                        raw = notification.Config().to_xml().decode()
                    return self._send(200, raw.encode()) or True
                raise S3Error("MethodNotAllowed")
            if "object-lock" in query:
                if cmd == "PUT":
                    self._allow(iampol.PUT_BUCKET_OBJECT_LOCK, bucket)
                    exists()
                    cfg = _try(lambda: objectlock.LockConfig.parse(payload))
                    if srv.bucket_meta.get_config(bucket,
                                                  "object-lock") is None:
                        # can only be set at creation in S3; MinIO allows
                        # updating the default rule iff lock was enabled
                        raise S3Error(
                            "InvalidBucketObjectLockConfiguration")
                    srv.bucket_meta.set_config(bucket, "object-lock",
                                               cfg.to_xml().decode())
                    return self._send(200) or True
                if cmd == "GET":
                    self._allow(iampol.GET_BUCKET_OBJECT_LOCK, bucket)
                    exists()
                    raw = srv.bucket_meta.get_config(bucket, "object-lock")
                    if raw is None:
                        raise S3Error(
                            "ObjectLockConfigurationNotFoundError")
                    return self._send(200, raw.encode()) or True
                raise S3Error("MethodNotAllowed")
            if "tagging" in query:
                if cmd == "PUT":
                    self._allow(iampol.PUT_BUCKET_TAGGING, bucket)
                    exists()
                    t = _try(lambda: tags.parse_xml(payload,
                                                    is_object=False))
                    srv.bucket_meta.set_config(bucket, "tagging",
                                               tags.to_xml(t).decode())
                    return self._send(200) or True
                if cmd == "GET":
                    self._allow(iampol.GET_BUCKET_TAGGING, bucket)
                    exists()
                    raw = srv.bucket_meta.get_config(bucket, "tagging")
                    if raw is None:
                        raise S3Error("NoSuchTagSet")
                    return self._send(200, raw.encode()) or True
                if cmd == "DELETE":
                    self._allow(iampol.PUT_BUCKET_TAGGING, bucket)
                    exists()
                    srv.bucket_meta.set_config(bucket, "tagging", None)
                    return self._send(204) or True
                raise S3Error("MethodNotAllowed")
            if "quota" in query:  # admin-style; also exposed here
                from ..bucket.quota import Quota
                if cmd == "PUT":
                    self._allow(iampol.ADMIN_ALL, bucket)
                    exists()
                    q = _try(lambda: Quota.parse(payload))
                    srv.bucket_meta.set_config(bucket, "quota",
                                               q.to_json().decode())
                    return self._send(200) or True
                if cmd == "GET":
                    self._allow(iampol.ADMIN_ALL, bucket)
                    exists()
                    raw = srv.bucket_meta.get_config(bucket, "quota") \
                        or '{"quota": 0, "quotatype": "hard"}'
                    return self._send(200, raw.encode(),
                                      content_type="application/json") \
                        or True
                raise S3Error("MethodNotAllowed")
            if "acl" in query:
                if cmd == "GET":
                    self._allow(iampol.GET_BUCKET_ACL, bucket)
                    exists()
                    return self._send(200, _canned_acl_xml()) or True
                if cmd == "PUT":
                    # only the private canned ACL is accepted
                    self._allow(iampol.PUT_BUCKET_ACL, bucket)
                    exists()
                    acl = self.headers.get("x-amz-acl", "private")
                    if acl != "private" or (payload and
                                            b"FULL_CONTROL" not in payload):
                        raise S3Error("NotImplemented")
                    return self._send(200) or True
                raise S3Error("MethodNotAllowed")
            if "cors" in query:
                self._allow(iampol.GET_BUCKET_LOCATION, bucket)
                exists()
                if cmd == "GET":
                    raise S3Error("NoSuchCORSConfiguration")
                raise S3Error("NotImplemented")
            return False

        def _bucket_api(self, bucket, query, payload):
            cmd = self.command
            if self._config_api(bucket, query, payload):
                return
            if cmd == "PUT" and "versioning" in query:
                self._allow(iampol.PUT_BUCKET_VERSIONING, bucket)
                return self._put_versioning(bucket, payload)
            if cmd == "GET" and "versioning" in query:
                self._allow(iampol.GET_BUCKET_VERSIONING, bucket)
                return self._get_versioning(bucket)
            if cmd == "GET" and "location" in query:
                self._allow(iampol.GET_BUCKET_LOCATION, bucket)
                root = ET.Element("LocationConstraint", xmlns=S3_NS)
                root.text = srv.region
                srv.layer.get_bucket_info(bucket)
                return self._send(200, _xml(root))
            if cmd == "GET" and "versions" in query:
                self._allow(iampol.LIST_BUCKET_VERSIONS, bucket)
                return self._list_object_versions(bucket, query)
            if cmd == "GET" and "events" in query:
                self._allow(iampol.LISTEN_NOTIFICATION, bucket)
                return self._listen_notification(bucket, query)
            if cmd == "POST" and "delete" in query:
                return self._delete_objects(bucket, payload)
            if cmd == "POST" and (self.headers.get("Content-Type") or ""
                                  ).startswith("multipart/form-data"):
                return self._post_policy_upload(bucket, payload)
            if cmd == "GET" and "uploads" in query:
                self._allow(iampol.LIST_MULTIPART_UPLOADS, bucket)
                return self._list_uploads(bucket, query)
            if cmd == "PUT":
                self._allow(iampol.CREATE_BUCKET, bucket)
                fresh_rec = False
                if srv.federation is not None:
                    from ..utils.fed_dns import BucketTaken
                    try:
                        fresh_rec = srv.federation.register(bucket)
                    except BucketTaken:
                        raise S3Error("BucketAlreadyExists") from None
                try:
                    srv.layer.make_bucket(bucket)
                except Exception:
                    if srv.federation is not None and fresh_rec:
                        srv.federation.unregister(bucket)
                    raise
                if self.headers.get("x-amz-bucket-object-lock-enabled",
                                    "").lower() == "true":
                    # lock implies versioning (cmd/bucket-handlers.go
                    # PutBucketHandler: object-lock buckets are versioned)
                    from ..bucket.objectlock import LockConfig
                    srv.bucket_meta.set_versioning(bucket, True)
                    srv.bucket_meta.set_config(
                        bucket, "object-lock",
                        LockConfig(enabled=True).to_xml().decode())
                return self._send(200, headers={"Location": f"/{bucket}"})
            if cmd == "HEAD":
                self._allow(iampol.LIST_BUCKET, bucket)
                srv.layer.get_bucket_info(bucket)
                return self._send(200)
            if cmd == "DELETE":
                self._allow(iampol.DELETE_BUCKET, bucket)
                srv.layer.delete_bucket(bucket)
                srv.bucket_meta.drop(bucket)
                if srv.federation is not None:
                    srv.federation.unregister(bucket)
                return self._send(204)
            if cmd == "GET":
                self._allow(iampol.LIST_BUCKET, bucket)
                return self._list_objects(bucket, query)
            raise S3Error("MethodNotAllowed")

        def _post_policy_upload(self, bucket, payload):
            """Browser POST upload (cmd/object-handlers.go
            PostPolicyBucketHandler): authenticate via the policy
            signature in the form, validate conditions, store the file
            field as the object."""
            from . import postpolicy
            try:
                fields, file_data, filename = postpolicy.parse_form(
                    payload, self.headers.get("Content-Type", ""))
                key = fields.get("key", "")
                if not key:
                    raise S3Error("InvalidArgument")
                key = key.replace("${filename}", filename)
                self.access_key = postpolicy.verify_signature(
                    srv.iam.lookup_secret, fields, srv.region)
                postpolicy.check_policy(
                    fields.get("policy", ""),
                    {**fields, "key": key, "bucket": bucket},
                    len(file_data))
            except sigv4.SigV4Error as e:
                raise S3Error(e.code if s3err.has(e.code)
                              else "AccessDenied") from e
            self._allow(iampol.PUT_OBJECT, f"{bucket}/{key}")
            if len(file_data) > MAX_OBJECT_SIZE:
                raise S3Error("EntityTooLarge")
            user_defined = {}
            if fields.get("content-type"):
                user_defined["content-type"] = fields["content-type"]
            for k, v in fields.items():
                if k.startswith("x-amz-meta-"):
                    user_defined[k] = v
            if fields.get("tagging"):
                from ..bucket import tags as btags
                try:
                    user_defined["x-amz-tagging"] = btags.to_header(
                        btags.parse_xml(fields["tagging"].encode()))
                except btags.TagError as e:
                    raise S3Error("InvalidTag") from e
            oi, hdrs = self._store_object(bucket, key, file_data,
                                          user_defined,
                                          "s3:ObjectCreated:Post")
            hdrs["Location"] = f"/{bucket}/{urllib.parse.quote(key)}"
            redirect = fields.get("success_action_redirect", "")
            if redirect:
                sep = "&" if "?" in redirect else "?"
                hdrs["Location"] = redirect + sep + urllib.parse.urlencode(
                    {"bucket": bucket, "key": key, "etag": f'"{oi.etag}"'})
                return self._send(303, headers=hdrs)
            status = fields.get("success_action_status", "204")
            if status == "201":
                root = ET.Element("PostResponse")
                ET.SubElement(root, "Location").text = hdrs["Location"]
                ET.SubElement(root, "Bucket").text = bucket
                ET.SubElement(root, "Key").text = key
                ET.SubElement(root, "ETag").text = hdrs["ETag"]
                return self._send(201, _xml(root), headers=hdrs)
            return self._send(200 if status == "200" else 204,
                              headers=hdrs)

        def _put_versioning(self, bucket, payload):
            srv.layer.get_bucket_info(bucket)
            try:
                root = ET.fromstring(payload)
                status = root.findtext(f"{{{S3_NS}}}Status") or \
                    root.findtext("Status") or ""
            except ET.ParseError as e:
                raise S3Error("MalformedXML") from e
            if status != "Enabled" and \
                    srv.bucket_meta.get_config(bucket,
                                               "object-lock") is not None:
                # object-lock buckets must stay versioned (AWS
                # InvalidBucketState)
                raise S3Error("InvalidBucketState")
            srv.bucket_meta.set_versioning(bucket, status == "Enabled")
            self._send(200)

        def _get_versioning(self, bucket):
            srv.layer.get_bucket_info(bucket)
            root = ET.Element("VersioningConfiguration", xmlns=S3_NS)
            doc = srv.bucket_meta.get(bucket).get("versioning")
            if doc:
                ET.SubElement(root, "Status").text = doc["status"]
            self._send(200, _xml(root))

        def _listen_notification(self, bucket, query):
            """Live event stream (cmd/listen-notification-handlers.go):
            newline-delimited JSON records, chunked; filters by prefix/
            suffix/event-name glob.  `timeout` bounds the stream so HTTP
            clients without explicit cancel (and tests) can use it."""
            import json as _json

            from ..bucket.notification import match_pattern
            srv.layer.get_bucket_info(bucket)
            q1 = {k: v[0] for k, v in query.items()}
            prefix = q1.get("prefix", "")
            suffix = q1.get("suffix", "")
            names = query.get("events", []) or ["*"]
            try:
                timeout = min(float(q1.get("timeout", 10) or 10), 300.0)
                max_events = int(q1.get("max-events", 1000) or 1000)
            except ValueError as e:
                raise S3Error("InvalidArgument") from e

            def want(item):
                if item["bucket"] != bucket:
                    return False
                key = item["key"]
                if prefix and not key.startswith(prefix):
                    return False
                if suffix and not key.endswith(suffix):
                    return False
                return any(n == "*" or match_pattern(n, item["name"])
                           for n in names)

            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def write_chunk(data: bytes):
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data + b"\r\n")
                self.wfile.flush()

            with srv.events.pubsub.subscribe(want) as sub:
                try:
                    for item in sub.drain(max_events, timeout):
                        line = _json.dumps(
                            {"Records": [item["record"]]}).encode() + b"\n"
                        write_chunk(line)
                except (BrokenPipeError, ConnectionResetError):
                    pass
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass

        def _encoding_type(self, q1):
            """encoding-type handling shared by every listing API:
            returns (escape_fn, enabled).  Keys may contain characters
            XML 1.0 cannot carry; url encoding (the awscli/boto3
            default) percent-encodes them in responses."""
            enc = q1.get("encoding-type", "")
            if enc and enc != "url":
                raise S3Error("InvalidArgument")
            if enc:
                return (lambda s: urllib.parse.quote(s or "", safe="/"),
                        True)
            return (lambda s: s), False

        def _list_objects(self, bucket, query):
            q1 = {k: v[0] for k, v in query.items()}
            v2 = q1.get("list-type") == "2"
            prefix = q1.get("prefix", "")
            delimiter = q1.get("delimiter", "")
            max_keys = min(int(q1.get("max-keys", 1000) or 1000), 1000)
            marker = q1.get("continuation-token" if v2 else "marker", "") \
                or q1.get("start-after", "")
            esc, enc = self._encoding_type(q1)
            res = srv.layer.list_objects(bucket, prefix, marker, delimiter,
                                         max_keys)
            name = "ListBucketResult"
            root = ET.Element(name, xmlns=S3_NS)
            ET.SubElement(root, "Name").text = bucket
            ET.SubElement(root, "Prefix").text = esc(prefix)
            if delimiter:
                ET.SubElement(root, "Delimiter").text = esc(delimiter)
            if enc:
                ET.SubElement(root, "EncodingType").text = "url"
            ET.SubElement(root, "MaxKeys").text = str(max_keys)
            ET.SubElement(root, "IsTruncated").text = \
                "true" if res.is_truncated else "false"
            if v2:
                ET.SubElement(root, "KeyCount").text = \
                    str(len(res.objects) + len(res.prefixes))
                if q1.get("continuation-token"):
                    # tokens are OPAQUE to clients: AWS excludes them
                    # from encoding-type, and clients echo them verbatim
                    # — encoding here would corrupt pagination
                    ET.SubElement(root, "ContinuationToken").text = \
                        q1["continuation-token"]
                if q1.get("start-after"):
                    ET.SubElement(root, "StartAfter").text = \
                        esc(q1["start-after"])
                if res.is_truncated:
                    ET.SubElement(root, "NextContinuationToken").text = \
                        res.next_marker
            else:
                ET.SubElement(root, "Marker").text = esc(marker)
                if res.is_truncated:
                    ET.SubElement(root, "NextMarker").text = \
                        esc(res.next_marker)
            fetch_owner = (not v2) or q1.get("fetch-owner") == "true"
            for o in res.objects:
                c = ET.SubElement(root, "Contents")
                ET.SubElement(c, "Key").text = esc(o.name)
                ET.SubElement(c, "LastModified").text = _iso_date(o.mod_time)
                ET.SubElement(c, "ETag").text = f'"{o.etag}"'
                ET.SubElement(c, "Size").text = str(_actual_size(o))
                ET.SubElement(c, "StorageClass").text = \
                    o.user_defined.get("x-amz-storage-class", "STANDARD")
                if fetch_owner:
                    owner = ET.SubElement(c, "Owner")
                    ET.SubElement(owner, "ID").text = "minio-tpu"
                    ET.SubElement(owner, "DisplayName").text = "minio-tpu"
            for p in res.prefixes:
                cp = ET.SubElement(root, "CommonPrefixes")
                ET.SubElement(cp, "Prefix").text = esc(p)
            self._send(200, _xml(root))

        def _list_object_versions(self, bucket, query):
            q1 = {k: v[0] for k, v in query.items()}
            prefix = q1.get("prefix", "")
            esc, enc = self._encoding_type(q1)
            versions = srv.layer.list_object_versions(bucket, prefix)
            root = ET.Element("ListVersionsResult", xmlns=S3_NS)
            ET.SubElement(root, "Name").text = bucket
            ET.SubElement(root, "Prefix").text = esc(prefix)
            if enc:
                ET.SubElement(root, "EncodingType").text = "url"
            ET.SubElement(root, "IsTruncated").text = "false"
            for o in versions:
                tag = "DeleteMarker" if o.delete_marker else "Version"
                v = ET.SubElement(root, tag)
                ET.SubElement(v, "Key").text = esc(o.name)
                ET.SubElement(v, "VersionId").text = o.version_id or "null"
                ET.SubElement(v, "IsLatest").text = \
                    "true" if o.is_latest else "false"
                ET.SubElement(v, "LastModified").text = _iso_date(o.mod_time)
                if not o.delete_marker:
                    ET.SubElement(v, "ETag").text = f'"{o.etag}"'
                    ET.SubElement(v, "Size").text = str(_actual_size(o))
                    ET.SubElement(v, "StorageClass").text = "STANDARD"
            self._send(200, _xml(root))

        def _list_uploads(self, bucket, query):
            q1 = {k: v[0] for k, v in query.items()}
            esc, enc = self._encoding_type(q1)
            uploads = srv.layer.list_multipart_uploads(
                bucket, q1.get("prefix", ""))
            root = ET.Element("ListMultipartUploadsResult", xmlns=S3_NS)
            ET.SubElement(root, "Bucket").text = bucket
            if enc:
                ET.SubElement(root, "EncodingType").text = "url"
            ET.SubElement(root, "IsTruncated").text = "false"
            for u in uploads:
                ue = ET.SubElement(root, "Upload")
                ET.SubElement(ue, "Key").text = esc(u.object_name)
                ET.SubElement(ue, "UploadId").text = u.upload_id
            self._send(200, _xml(root))

        def _delete_objects(self, bucket, payload):
            try:
                root = ET.fromstring(payload)
            except ET.ParseError as e:
                raise S3Error("MalformedXML") from e
            ns = f"{{{S3_NS}}}"
            quiet = (root.findtext(f"{ns}Quiet") or
                     root.findtext("Quiet") or "") == "true"
            out = ET.Element("DeleteResult", xmlns=S3_NS)
            versioned = srv.bucket_meta.versioning_enabled(bucket)
            for obj in (root.findall(f"{ns}Object") +
                        root.findall("Object")):
                key = obj.findtext(f"{ns}Key") or obj.findtext("Key")
                vid = obj.findtext(f"{ns}VersionId") or \
                    obj.findtext("VersionId")
                try:
                    self._allow(iampol.DELETE_OBJECT, f"{bucket}/{key}")
                    self._check_retention(bucket, key, vid)
                    tiered_ud = self._tiered_meta_of(bucket, key, vid,
                                                     versioned)
                    res = srv.layer.delete_object(
                        bucket, key,
                        ol.ObjectOptions(version_id=vid,
                                         versioned=versioned))
                    if tiered_ud is not None:
                        srv.transition.delete_tiered(tiered_ud)
                    if not quiet:
                        d = ET.SubElement(out, "Deleted")
                        ET.SubElement(d, "Key").text = key
                        if res.delete_marker:
                            ET.SubElement(d, "DeleteMarker").text = "true"
                            ET.SubElement(d,
                                          "DeleteMarkerVersionId").text = \
                                res.version_id
                except Exception as e:  # noqa: BLE001
                    if isinstance(e, S3Error):
                        api = e.api
                    elif isinstance(e, ol.ObjectLayerError):
                        api = s3err.from_object_error(e)
                    else:
                        api = s3err.get("InternalError")
                    err = ET.SubElement(out, "Error")
                    ET.SubElement(err, "Key").text = key
                    ET.SubElement(err, "Code").text = api.code
                    ET.SubElement(err, "Message").text = api.description
            self._send(200, _xml(out))

        # -- object APIs ---------------------------------------------------

        def _object_api(self, bucket, key, query, payload):
            cmd = self.command
            resource = f"{bucket}/{key}"
            if "tagging" in query:
                return self._object_tagging(bucket, key, query, payload)
            if "retention" in query:
                return self._object_retention(bucket, key, query, payload)
            if "legal-hold" in query:
                return self._object_legal_hold(bucket, key, query, payload)
            if "acl" in query:
                if cmd == "GET":
                    self._allow(iampol.GET_OBJECT_ACL, resource)
                    srv.layer.get_object_info(bucket, key)
                    return self._send(200, _canned_acl_xml())
                if cmd == "PUT":
                    self._allow(iampol.PUT_OBJECT_ACL, resource)
                    if self.headers.get("x-amz-acl", "private") != "private":
                        raise S3Error("NotImplemented")
                    return self._send(200)
                raise S3Error("MethodNotAllowed")
            if cmd == "POST" and "select" in query and \
                    query.get("select-type") == ["2"]:
                self._allow(iampol.GET_OBJECT, resource)
                return self._select_object(bucket, key, payload)
            if cmd == "POST" and "uploads" in query:
                self._allow(iampol.PUT_OBJECT, resource)
                return self._create_multipart(bucket, key)
            if cmd == "POST" and "uploadId" in query:
                self._allow(iampol.PUT_OBJECT, resource)
                return self._complete_multipart(bucket, key, query, payload)
            if cmd == "PUT" and "uploadId" in query and \
                    "x-amz-copy-source" in self.headers:
                self._allow(iampol.PUT_OBJECT, resource)
                return self._upload_part_copy(bucket, key, query)
            if cmd == "PUT" and "uploadId" in query:
                self._allow(iampol.PUT_OBJECT, resource)
                return self._upload_part(bucket, key, query, payload)
            if cmd == "PUT" and "x-amz-copy-source" in self.headers:
                self._allow(iampol.PUT_OBJECT, resource)
                return self._copy_object(bucket, key, query)
            if cmd == "DELETE" and "uploadId" in query:
                self._allow(iampol.ABORT_MULTIPART, resource)
                srv.layer.abort_multipart_upload(bucket, key,
                                                 query["uploadId"][0])
                return self._send(204)
            if cmd == "GET" and "uploadId" in query:
                self._allow(iampol.LIST_PARTS, resource)
                return self._list_parts(bucket, key, query)
            if cmd == "POST" and "restore" in query:
                self._allow("s3:RestoreObject", resource)
                return self._restore_object(bucket, key, query, payload)
            if cmd == "PUT":
                self._allow(iampol.PUT_OBJECT, resource)
                return self._put_object(bucket, key, query, payload)
            if cmd in ("GET", "HEAD"):
                self._allow(
                    iampol.GET_OBJECT_VERSION if query.get("versionId")
                    else iampol.GET_OBJECT, resource)
                return self._get_object(bucket, key, query,
                                        head=(cmd == "HEAD"))
            if cmd == "DELETE":
                self._allow(
                    iampol.DELETE_OBJECT_VERSION if query.get("versionId")
                    else iampol.DELETE_OBJECT, resource)
                return self._delete_object(bucket, key, query)
            raise S3Error("MethodNotAllowed")

        # -- object subresources (tagging/retention/legal-hold) ------------

        TAG_KEY = "x-amz-tagging"  # metadata key holding url-encoded tags

        def _vid(self, query) -> str | None:
            vid = query.get("versionId", [None])[0]
            return "" if vid == "null" else vid

        def _object_tagging(self, bucket, key, query, payload):
            from ..bucket import tags as btags
            resource = f"{bucket}/{key}"
            vid = self._vid(query)
            if self.command == "PUT":
                self._allow(iampol.PUT_OBJECT_TAGGING, resource)
                t = _try(lambda: btags.parse_xml(payload))
                oi = srv.layer.put_object_metadata(
                    bucket, key, vid, {self.TAG_KEY: btags.to_header(t)})
                srv.notify("s3:ObjectCreated:PutTagging", bucket, oi)
                return self._send(200)
            if self.command == "GET":
                self._allow(iampol.GET_OBJECT_TAGGING, resource)
                oi = srv.layer.get_object_info(
                    bucket, key, ol.ObjectOptions(version_id=vid))
                t = btags.parse_header(
                    oi.user_defined.get(self.TAG_KEY, ""))
                return self._send(200, btags.to_xml(t))
            if self.command == "DELETE":
                self._allow(iampol.DELETE_OBJECT_TAGGING, resource)
                oi = srv.layer.put_object_metadata(
                    bucket, key, vid, {}, removes=(self.TAG_KEY,))
                srv.notify("s3:ObjectCreated:DeleteTagging", bucket, oi)
                return self._send(204)
            raise S3Error("MethodNotAllowed")

        def _object_retention(self, bucket, key, query, payload):
            from ..bucket import objectlock as olock
            resource = f"{bucket}/{key}"
            vid = self._vid(query)
            if self.command == "PUT":
                self._allow(iampol.PUT_OBJECT_RETENTION, resource)
                if srv.bucket_meta.get_config(bucket, "object-lock") is None:
                    raise S3Error("InvalidRequest")
                ret = _try(lambda: olock.Retention.parse(payload))
                # tightening is always allowed; loosening COMPLIANCE is not
                oi = srv.layer.get_object_info(
                    bucket, key, ol.ObjectOptions(version_id=vid))
                cur = olock.Retention.from_metadata(oi.user_defined)
                if cur.active() and cur.mode == olock.COMPLIANCE and (
                        ret.retain_until < cur.retain_until or
                        ret.mode != olock.COMPLIANCE):
                    raise S3Error("ObjectLocked")
                if cur.active() and cur.mode == olock.GOVERNANCE and \
                        not self._governance_bypass(resource):
                    if ret.retain_until < cur.retain_until or \
                            ret.mode != cur.mode:
                        raise S3Error("ObjectLocked")
                oi = srv.layer.put_object_metadata(bucket, key, vid, {
                    olock.AMZ_OBJECT_LOCK_MODE: ret.mode,
                    olock.AMZ_OBJECT_LOCK_RETAIN_UNTIL:
                        ret.retain_until.astimezone(
                            datetime.timezone.utc).strftime(
                                "%Y-%m-%dT%H:%M:%SZ"),
                })
                srv.notify("s3:ObjectCreated:PutRetention", bucket, oi)
                return self._send(200)
            if self.command == "GET":
                self._allow(iampol.GET_OBJECT_RETENTION, resource)
                oi = srv.layer.get_object_info(
                    bucket, key, ol.ObjectOptions(version_id=vid))
                ret = olock.Retention.from_metadata(oi.user_defined)
                if not ret.mode:
                    raise S3Error("NoSuchObjectLockConfiguration")
                return self._send(200, ret.to_xml())
            raise S3Error("MethodNotAllowed")

        def _object_legal_hold(self, bucket, key, query, payload):
            from ..bucket import objectlock as olock
            resource = f"{bucket}/{key}"
            vid = self._vid(query)
            if self.command == "PUT":
                self._allow(iampol.PUT_OBJECT_LEGAL_HOLD, resource)
                if srv.bucket_meta.get_config(bucket, "object-lock") is None:
                    raise S3Error("InvalidRequest")
                status = _try(lambda: olock.legal_hold_from_xml(payload))
                oi = srv.layer.put_object_metadata(
                    bucket, key, vid,
                    {olock.AMZ_OBJECT_LOCK_LEGAL_HOLD: status})
                srv.notify("s3:ObjectCreated:PutLegalHold", bucket, oi)
                return self._send(200)
            if self.command == "GET":
                self._allow(iampol.GET_OBJECT_LEGAL_HOLD, resource)
                oi = srv.layer.get_object_info(
                    bucket, key, ol.ObjectOptions(version_id=vid))
                status = oi.user_defined.get(
                    olock.AMZ_OBJECT_LOCK_LEGAL_HOLD, "OFF")
                return self._send(200, olock.legal_hold_to_xml(status))
            raise S3Error("MethodNotAllowed")

        def _governance_bypass(self, resource: str) -> bool:
            if self.headers.get("x-amz-bypass-governance-retention",
                                "").lower() != "true":
                return False
            try:
                self._allow(iampol.BYPASS_GOVERNANCE, resource)
                return True
            except S3Error:
                return False

        def _select_object(self, bucket, key, payload):
            from . import select as s3select
            _, data = self._fetch_plain(bucket, key)
            try:
                out = s3select.run(payload, data)
            except s3select.SelectError as e:
                raise S3Error(e.code) from e
            self._send(200, out,
                       content_type="application/octet-stream")

        def _fetch_plain(self, bucket, key):
            """Full object bytes after decryption (honoring SSE-C request
            headers) and decompression — the decoded-object fetch shared
            by Select and other whole-object consumers."""
            from .. import compress as mtc
            from ..crypto import sse as csse
            oi = srv.layer.get_object_info(bucket, key)
            if csse.is_encrypted(oi.user_defined):
                enc = csse.ObjectEncryption.open(
                    oi.user_defined, bucket, key, self.headers, srv.kms)
                data = csse.decrypt_object_range(
                    enc, oi.user_defined, oi.size,
                    lambda o, n: srv.layer.get_object(
                        bucket, key, o, n)[1], 0, -1, oi.parts)
            else:
                _, data = srv.layer.get_object(bucket, key)
            if mtc.META_COMPRESSION in oi.user_defined:
                data = mtc.decompress_stream(data)
            return oi, data

        def _check_quota(self, bucket: str, nbytes: int) -> None:
            """Hard-quota admission (cmd/bucket-quota.go); needs the
            crawler's usage cache to be attached."""
            if srv.usage is None:
                return
            from ..bucket.quota import Quota
            raw = srv.bucket_meta.get_config(bucket, "quota")
            if raw and not Quota.parse(raw.encode()).allows(
                    srv.usage.bucket_size(bucket), nbytes):
                raise S3Error("AdminBucketQuotaExceeded")

        # -- SSE helpers (cmd/encryption-v1.go) ----------------------------

        def _bucket_sse_algo(self, bucket: str) -> str:
            """Bucket default-encryption algorithm, '' when unset."""
            from ..bucket.encryption import SSEConfig
            raw = srv.bucket_meta.get_config(bucket, "encryption")
            if not raw:
                return ""
            try:
                return SSEConfig.parse(raw.encode()).algorithm
            except ValueError:
                return ""

        def _sse_for_put(self, bucket: str, key: str,
                         user_defined: dict) -> "object | None":
            """EncryptRequest analog: decide whether this PUT is SSE and
            mint the sealed object key into user_defined."""
            from ..crypto import sse as csse
            kind = csse.requested_sse(self.headers,
                                      self._bucket_sse_algo(bucket))
            if not kind:
                return None
            enc = csse.ObjectEncryption.new(kind, bucket, key,
                                            self.headers, srv.kms)
            user_defined.update(enc.meta)
            return enc

        def _compress_for_put(self, key: str, user_defined: dict,
                              payload: bytes) -> bytes:
            """Transparent compression (newS2CompressReader analog):
            applied BEFORE encryption, recorded via internal metadata with
            the original size for listings/HEAD."""
            from .. import compress as mtc
            from ..crypto import sse as csse
            if srv.config.get("compression", "enable") != "on":
                return payload
            exts = [e for e in srv.config.get(
                "compression", "extensions").split(",") if e]
            types = [t for t in srv.config.get(
                "compression", "mime_types").split(",") if t]
            ct = user_defined.get("content-type", "")
            if not mtc.is_compressible(key, ct, len(payload), exts, types):
                return payload
            user_defined[mtc.META_COMPRESSION] = mtc.COMPRESSION_ALGO
            user_defined[csse.META_ACTUAL_SIZE] = str(len(payload))
            return mtc.compress_stream(payload)

        def _tagging_header_meta(self) -> dict[str, str]:
            """Validated x-amz-tagging header as metadata entries."""
            tag_hdr = self.headers.get("x-amz-tagging")
            if not tag_hdr:
                return {}
            from ..bucket import tags as btags
            _try(lambda: btags.parse_header(tag_hdr))
            return {self.TAG_KEY: tag_hdr}

        def _create_multipart(self, bucket, key):
            user_defined = {}
            ct = self.headers.get("Content-Type")
            if ct:
                user_defined["content-type"] = ct
            for h, v in self.headers.items():
                if h.lower().startswith("x-amz-meta-"):
                    user_defined[h.lower()] = v
            # same admission rules as PutObject: tagging header + object
            # lock defaults (a multipart upload must not dodge WORM)
            user_defined.update(self._tagging_header_meta())
            user_defined.update(self._lock_headers(bucket, key))
            from ..crypto import sse as csse
            self._sse_for_put(bucket, key, user_defined)
            versioned = srv.bucket_meta.versioning_enabled(bucket)
            uid = srv.layer.new_multipart_upload(
                bucket, key, ol.PutObjectOptions(
                    user_defined=user_defined, versioned=versioned,
                    parity=self._storage_class_parity(user_defined)))
            root = ET.Element("InitiateMultipartUploadResult", xmlns=S3_NS)
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "Key").text = key
            ET.SubElement(root, "UploadId").text = uid
            self._send(200, _xml(root),
                       headers=csse.response_headers(user_defined))

        def _upload_part(self, bucket, key, query, payload):
            uid = query["uploadId"][0]
            try:
                part_num = int(query["partNumber"][0])
            except (KeyError, ValueError) as e:
                raise S3Error("InvalidArgument") from e
            self._check_quota(bucket, len(payload))
            payload, sse_hdrs = self._encrypt_part(bucket, key, uid,
                                                   payload)
            pi = srv.layer.put_object_part(bucket, key, uid, part_num,
                                           payload)
            self._send(200, headers={"ETag": f'"{pi.etag}"', **sse_hdrs})

        def _encrypt_part(self, bucket, key, uid,
                          payload) -> tuple[bytes, dict]:
            """Encrypt one part under the upload's sealed OEK as its own
            DARE stream (SSE-C requires the key headers on every part)."""
            from ..crypto import sse as csse
            mp = srv.layer.get_multipart_info(bucket, key, uid)
            if not csse.is_encrypted(mp.user_defined):
                return payload, {}
            enc = csse.ObjectEncryption.open(mp.user_defined, bucket, key,
                                             self.headers, srv.kms)
            return enc.encrypt(payload), \
                csse.response_headers(mp.user_defined)

        def _complete_multipart(self, bucket, key, query, payload):
            uid = query["uploadId"][0]
            try:
                root = ET.fromstring(payload)
            except ET.ParseError as e:
                raise S3Error("MalformedXML") from e
            ns = f"{{{S3_NS}}}"
            parts = []
            for p in root.findall(f"{ns}Part") + root.findall("Part"):
                num = p.findtext(f"{ns}PartNumber") or \
                    p.findtext("PartNumber")
                etag = p.findtext(f"{ns}ETag") or p.findtext("ETag") or ""
                if num is None or not num.isdigit():
                    raise S3Error("MalformedXML")
                parts.append((int(num), etag.strip('"')))
            # SSE needs no extra bookkeeping here: the part table committed
            # atomically with xl.meta carries per-part ciphertext sizes
            # (each part is its own DARE stream; ObjectInfo.parts)
            oi = srv.layer.complete_multipart_upload(bucket, key, uid, parts)
            out = ET.Element("CompleteMultipartUploadResult", xmlns=S3_NS)
            ET.SubElement(out, "Location").text = \
                f"{srv.endpoint}/{bucket}/{key}"
            ET.SubElement(out, "Bucket").text = bucket
            ET.SubElement(out, "Key").text = key
            ET.SubElement(out, "ETag").text = f'"{oi.etag}"'
            hdrs = {}
            if oi.version_id:
                hdrs["x-amz-version-id"] = oi.version_id
            srv.notify("s3:ObjectCreated:CompleteMultipartUpload", bucket,
                       oi)
            srv.replicate(bucket, oi)
            self._send(200, _xml(out), headers=hdrs)

        def _list_parts(self, bucket, key, query):
            uid = query["uploadId"][0]
            parts = srv.layer.list_object_parts(bucket, key, uid)
            root = ET.Element("ListPartsResult", xmlns=S3_NS)
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "Key").text = key
            ET.SubElement(root, "UploadId").text = uid
            ET.SubElement(root, "IsTruncated").text = "false"
            for p in parts:
                pe = ET.SubElement(root, "Part")
                ET.SubElement(pe, "PartNumber").text = str(p.part_number)
                ET.SubElement(pe, "ETag").text = f'"{p.etag}"'
                ET.SubElement(pe, "Size").text = str(p.size)
            self._send(200, _xml(root))

        # -- streaming PUT (cmd/erasure-encode.go block pipeline over the
        # socket: body is never buffered; 5 GiB single PUT works in
        # O(batch) memory) ------------------------------------------------

        def _try_stream_put(self, path, bucket, key, query) -> bool:
            """Route large plain object PUTs / part uploads through the
            streaming pipeline.  Returns True when the request was fully
            handled (success or error); False falls back to the buffered
            path WITHOUT having consumed any body bytes."""
            if self.command != "PUT" or not bucket or not key:
                return False
            if path.startswith("/minio-tpu/") or bucket == "minio-tpu" \
                    or not _BUCKET_RE.match(bucket):
                return False
            if any(q in query for q in ("tagging", "retention",
                                        "legal-hold", "acl")):
                return False
            if "x-amz-copy-source" in self.headers:
                return False
            cl_hdr = self.headers.get("Content-Length")
            if cl_hdr is None:
                return False
            try:
                cl = int(cl_hdr)
            except ValueError:
                return False
            if cl <= STREAM_PUT_THRESHOLD:
                return False
            try:
                if cl > MAX_PUT_SIZE:
                    raise S3Error("EntityTooLarge")
                # only layers with a REAL streaming override may take
                # this route — the ObjectLayer default would buffer the
                # whole body, bypassing max_body_size
                if type(srv.layer).put_object_stream \
                        is ol.ObjectLayer.put_object_stream:
                    if cl > srv.max_body_size:
                        raise S3Error("EntityTooLarge")
                    return False
                # SSE and transparent compression transform the body and
                # are not streamed yet: those bodies take the buffered
                # path (bounded by max_body_size)
                from ..crypto import sse as csse
                if "uploadId" in query:
                    try:
                        mp = srv.layer.get_multipart_info(
                            bucket, key, query["uploadId"][0])
                        transforming = csse.is_encrypted(mp.user_defined)
                    except Exception:  # noqa: BLE001 — invalid upload id
                        return False   # buffered path raises it properly
                else:
                    transforming = bool(csse.requested_sse(
                        self.headers, self._bucket_sse_algo(bucket))) \
                        or self._compression_eligible(key, cl)
                if transforming:
                    if cl > srv.max_body_size:
                        raise S3Error("EntityTooLarge")
                    return False
            except S3Error as e:
                self._fail(e, path)
                self.close_connection = True
                return True
            # committed to streaming from here: any failure must be
            # answered in-line and the (half-read) connection dropped
            try:
                reader = self._auth_stream(path, query)
                self._rx_bytes = cl
                from ..admin.metrics import GLOBAL as mtr
                mtr.inc("mt_s3_rx_bytes_total", value=cl)
                if "uploadId" in query:
                    self._stream_upload_part(bucket, key, query, reader,
                                             cl)
                else:
                    self._stream_put_object(bucket, key, reader, cl)
            except Exception as e:  # noqa: BLE001 — XML like dispatch
                self._fail(e, path)
                self.close_connection = True
            return True

        def _compression_eligible(self, key: str, size: int) -> bool:
            from .. import compress as mtc
            if srv.config.get("compression", "enable") != "on":
                return False
            exts = [e for e in srv.config.get(
                "compression", "extensions").split(",") if e]
            types = [t for t in srv.config.get(
                "compression", "mime_types").split(",") if t]
            ct = self.headers.get("Content-Type", "")
            return mtc.is_compressible(key, ct, size, exts, types)

        def _auth_stream(self, path, query):
            """Authenticate a PUT without buffering its body; returns the
            verified body reader (signature first, digests checked at
            EOF before the object layer commits)."""
            self._query_token = query.get("X-Amz-Security-Token", [""])[0]
            cl = int(self.headers["Content-Length"])
            hdrs = {k: v for k, v in self.headers.items()}
            lookup = srv.iam.lookup_secret
            md5_hdr = self.headers.get("Content-MD5")
            want_md5 = None
            if md5_hdr:
                import base64
                try:
                    want_md5 = base64.b64decode(md5_hdr)
                except Exception as e:
                    raise S3Error("InvalidDigest") from e
            sha = self.headers.get("x-amz-content-sha256")
            try:
                if "Authorization" not in hdrs and \
                        "X-Amz-Signature" not in query and \
                        not ("Signature" in query and
                             "AWSAccessKeyId" in query):
                    self.access_key = ""
                    body = _BodyReader(
                        self.rfile, cl,
                        sha256_hex=(sha if sha and
                                    sha != sigv4.UNSIGNED_PAYLOAD
                                    else None),
                        md5_digest=want_md5)
                elif hdrs.get("Authorization", "").startswith("AWS "):
                    from . import sigv2
                    self.access_key = sigv2.verify_request(
                        lookup, self.command, path, query, hdrs)
                    body = _BodyReader(self.rfile, cl,
                                       md5_digest=want_md5)
                elif "Signature" in query and "AWSAccessKeyId" in query:
                    from . import sigv2
                    self.access_key = sigv2.verify_presigned(
                        lookup, self.command, path, query, hdrs)
                    body = _BodyReader(self.rfile, cl,
                                       md5_digest=want_md5)
                elif "X-Amz-Signature" in query:
                    self.access_key = sigv4.verify_presigned(
                        lookup, self.command, path, query, hdrs,
                        region=srv.region)
                    body = _BodyReader(self.rfile, cl,
                                       md5_digest=want_md5)
                elif sha == sigv4.STREAMING_PAYLOAD:
                    self.access_key, key, seed, amz_date, scope = \
                        sigv4.verify_request_streaming(
                            lookup, self.command, path, query, hdrs,
                            region=srv.region)
                    framed = _BodyReader(self.rfile, cl)
                    body = sigv4.ChunkedStreamReader(framed, key, seed,
                                                     amz_date, scope)
                    if want_md5 is not None:
                        body = _MD5Reader(body, want_md5)
                else:
                    sha_eff = sha or sigv4.UNSIGNED_PAYLOAD
                    self.access_key = sigv4.verify_request(
                        lookup, self.command, path, query, hdrs, sha_eff,
                        region=srv.region)
                    body = _BodyReader(
                        self.rfile, cl,
                        sha256_hex=(sha_eff
                                    if sha_eff != sigv4.UNSIGNED_PAYLOAD
                                    else None),
                        md5_digest=want_md5)
            except sigv4.SigV4Error as e:
                raise S3Error(e.code) from e
            self._check_session_token()
            return body

        def _stream_put_object(self, bucket, key, reader, cl: int):
            self._allow(iampol.PUT_OBJECT, f"{bucket}/{key}")
            user_defined = {}
            ct = self.headers.get("Content-Type")
            if ct:
                user_defined["content-type"] = ct
            for h, v in self.headers.items():
                if h.lower().startswith("x-amz-meta-"):
                    user_defined[h.lower()] = v
            user_defined.update(self._tagging_header_meta())
            user_defined.update(self._lock_headers(bucket, key))
            self._check_quota(bucket, cl)
            versioned = srv.bucket_meta.versioning_enabled(bucket)
            tiered_ud = None if versioned else \
                self._tiered_meta_of(bucket, key, "", False)
            oi = srv.layer.put_object_stream(
                bucket, key, reader,
                ol.PutObjectOptions(
                    user_defined=user_defined, versioned=versioned,
                    parity=self._storage_class_parity(user_defined)))
            if tiered_ud is not None:
                srv.transition.delete_tiered(tiered_ud)
            hdrs = {"ETag": f'"{oi.etag}"'}
            if oi.version_id:
                hdrs["x-amz-version-id"] = oi.version_id
            srv.notify("s3:ObjectCreated:Put", bucket, oi)
            srv.replicate(bucket, oi)
            self._send(200, headers=hdrs)

        def _stream_upload_part(self, bucket, key, query, reader,
                                cl: int):
            self._allow(iampol.PUT_OBJECT, f"{bucket}/{key}")
            uid = query["uploadId"][0]
            try:
                part_num = int(query["partNumber"][0])
            except (KeyError, ValueError) as e:
                raise S3Error("InvalidArgument") from e
            self._check_quota(bucket, cl)
            pi = srv.layer.put_object_part(bucket, key, uid, part_num,
                                           reader)
            self._send(200, headers={"ETag": f'"{pi.etag}"'})

        def _put_object(self, bucket, key, query, payload):
            if "Content-Length" not in self.headers:
                raise S3Error("MissingContentLength")
            if len(payload) > MAX_OBJECT_SIZE:
                raise S3Error("EntityTooLarge")
            md5_hdr = self.headers.get("Content-MD5")
            if md5_hdr:
                import base64
                try:
                    want = base64.b64decode(md5_hdr)
                except Exception as e:
                    raise S3Error("InvalidDigest") from e
                if hashlib.md5(payload).digest() != want:
                    raise S3Error("BadDigest")
            user_defined = {}
            ct = self.headers.get("Content-Type")
            if ct:
                user_defined["content-type"] = ct
            for h, v in self.headers.items():
                if h.lower().startswith("x-amz-meta-"):
                    user_defined[h.lower()] = v
            user_defined.update(self._tagging_header_meta())
            oi, hdrs = self._store_object(bucket, key, payload,
                                          user_defined,
                                          "s3:ObjectCreated:Put")
            self._send(200, headers=hdrs)

        def _store_object(self, bucket, key, payload, user_defined,
                          event_name):
            """Shared tail of every simple write path (PUT and POST
            policy): quota, compression, SSE, lock defaults, store,
            notify, replicate.  Returns (oi, response_headers)."""
            user_defined.update(self._lock_headers(bucket, key))
            self._check_quota(bucket, len(payload))
            versioned = srv.bucket_meta.versioning_enabled(bucket)
            # unversioned overwrite replaces the null version: remember
            # its tiered bytes, freed only AFTER the new write commits
            # (an early free would destroy data if this PUT fails)
            tiered_ud = None if versioned else \
                self._tiered_meta_of(bucket, key, "", False)
            from ..crypto import sse as csse
            payload = self._compress_for_put(key, user_defined, payload)
            enc = self._sse_for_put(bucket, key, user_defined)
            if enc is not None:
                payload = enc.encrypt(payload)
            oi = srv.layer.put_object(
                bucket, key, payload,
                ol.PutObjectOptions(
                    user_defined=user_defined, versioned=versioned,
                    parity=self._storage_class_parity(user_defined)))
            if tiered_ud is not None:
                srv.transition.delete_tiered(tiered_ud)
            hdrs = {"ETag": f'"{oi.etag}"'}
            hdrs.update(csse.response_headers(user_defined))
            if oi.version_id:
                hdrs["x-amz-version-id"] = oi.version_id
            srv.notify(event_name, bucket, oi)
            srv.replicate(bucket, oi)
            return oi, hdrs

        # -- CopyObject / UploadPartCopy (cmd/object-handlers.go:886,
        # cmd/object-multipart-handlers.go CopyObjectPartHandler) ----------

        def _parse_copy_source(self) -> tuple[str, str, str | None]:
            """x-amz-copy-source -> (bucket, key, version_id).  The
            versionId qualifier is split off the RAW header first — a
            percent-encoded '?' inside the key must stay part of the key."""
            raw = self.headers.get("x-amz-copy-source", "")
            vid = None
            if "?versionId=" in raw:
                raw, vid = raw.split("?versionId=", 1)
                if vid == "null":
                    vid = ""
            src = urllib.parse.unquote(raw).lstrip("/")
            if "/" not in src:
                raise S3Error("InvalidCopySource")
            sbucket, skey = src.split("/", 1)
            if not sbucket or not skey:
                raise S3Error("InvalidCopySource")
            return sbucket, skey, vid

        def _read_copy_source(self, offset: int = 0, length: int = -1
                              ) -> tuple["ol.ObjectInfo", bytes, int]:
            """Fetch (and decrypt, honoring copy-source SSE-C headers) the
            copy source; returns (info, plaintext, plaintext_size)."""
            from ..crypto import sse as csse
            sbucket, skey, svid = self._parse_copy_source()
            self._allow(iampol.GET_OBJECT, f"{sbucket}/{skey}")
            opts = ol.ObjectOptions(version_id=svid)
            soi = srv.layer.get_object_info(sbucket, skey, opts)
            from ..objectlayer import tiering as _tr
            if _tr.is_transitioned(soi.user_defined) and \
                    not _tr.restore_valid(soi.user_defined):
                # archived source: copying the stub would silently write
                # a 0-byte destination
                raise S3Error("InvalidObjectState")
            # conditional copy headers (checkCopyObjectPreconditions) —
            # checked on metadata alone, BEFORE any data is read
            if_match = self.headers.get("x-amz-copy-source-if-match")
            if_none = self.headers.get("x-amz-copy-source-if-none-match")
            if if_match and if_match.strip('"') != soi.etag:
                raise S3Error("PreconditionFailed")
            if if_none and if_none.strip('"') == soi.etag:
                raise S3Error("PreconditionFailed")
            from .. import compress as mtc
            compressed = mtc.META_COMPRESSION in soi.user_defined
            if csse.is_encrypted(soi.user_defined):
                enc = csse.ObjectEncryption.open(
                    soi.user_defined, sbucket, skey, self.headers,
                    srv.kms, copy_source=True)
                if not compressed:
                    size = csse.decrypted_size(soi.user_defined, soi.size,
                                               soi.parts)
                    data = csse.decrypt_object_range(
                        enc, soi.user_defined, soi.size,
                        lambda o, n: srv.layer.get_object(
                            sbucket, skey, o, n, opts)[1], offset, length,
                        soi.parts)
                    return soi, data, size
                inner = csse.decrypt_object_range(
                    enc, soi.user_defined, soi.size,
                    lambda o, n: srv.layer.get_object(
                        sbucket, skey, o, n, opts)[1], 0, -1, soi.parts)
            elif not compressed:
                size = soi.size
                _, data = srv.layer.get_object(sbucket, skey, offset,
                                               length, opts)
                return soi, data, size
            else:
                _, inner = srv.layer.get_object(sbucket, skey, 0, -1,
                                                opts)
            full = mtc.decompress_stream(inner)
            data = full[offset:] if length < 0 \
                else full[offset:offset + length]
            return soi, data, len(full)

        def _copy_object(self, bucket, key, query):
            from ..crypto import sse as csse
            sbucket, skey, svid = self._parse_copy_source()
            soi, data, _ = self._read_copy_source()
            directive = self.headers.get("x-amz-metadata-directive",
                                         "COPY")
            user_defined: dict[str, str] = {}
            if directive == "REPLACE":
                ct = self.headers.get("Content-Type")
                if ct:
                    user_defined["content-type"] = ct
                for h, v in self.headers.items():
                    if h.lower().startswith("x-amz-meta-"):
                        user_defined[h.lower()] = v
            else:
                user_defined = {
                    k: v for k, v in soi.user_defined.items()
                    if k.startswith("x-amz-meta-") or k == "content-type"}
            tag_directive = self.headers.get("x-amz-tagging-directive",
                                             "COPY")
            if tag_directive == "REPLACE":
                user_defined.update(self._tagging_header_meta())
            elif soi.user_defined.get(self.TAG_KEY):
                user_defined[self.TAG_KEY] = soi.user_defined[self.TAG_KEY]
            user_defined.update(self._lock_headers(bucket, key))
            data = self._compress_for_put(key, user_defined, data)
            enc = self._sse_for_put(bucket, key, user_defined)
            sse_changed = enc is not None or \
                csse.is_encrypted(soi.user_defined)
            if sbucket == bucket and skey == key and svid is None and \
                    directive != "REPLACE" and not sse_changed:
                raise S3Error("InvalidCopyDest")
            self._check_quota(bucket, len(data))
            if enc is not None:
                data = enc.encrypt(data)
            versioned = srv.bucket_meta.versioning_enabled(bucket)
            oi = srv.layer.put_object(
                bucket, key, data,
                ol.PutObjectOptions(
                    user_defined=user_defined, versioned=versioned,
                    parity=self._storage_class_parity(user_defined)))
            root = ET.Element("CopyObjectResult", xmlns=S3_NS)
            ET.SubElement(root, "ETag").text = f'"{oi.etag}"'
            ET.SubElement(root, "LastModified").text = _iso_date(oi.mod_time)
            hdrs = dict(csse.response_headers(user_defined))
            if oi.version_id:
                hdrs["x-amz-version-id"] = oi.version_id
            if svid is not None:
                hdrs["x-amz-copy-source-version-id"] = svid or "null"
            srv.notify("s3:ObjectCreated:Copy", bucket, oi)
            srv.replicate(bucket, oi)
            self._send(200, _xml(root), headers=hdrs)

        def _upload_part_copy(self, bucket, key, query):
            uid = query["uploadId"][0]
            try:
                part_num = int(query["partNumber"][0])
            except (KeyError, ValueError) as e:
                raise S3Error("InvalidArgument") from e
            offset, length = 0, -1
            crng = self.headers.get("x-amz-copy-source-range")
            if crng:
                offset, length = _parse_range(crng)
                if offset < 0:
                    raise S3Error("InvalidRange")
            _, data, _ = self._read_copy_source(offset, length)
            self._check_quota(bucket, len(data))
            data, _ = self._encrypt_part(bucket, key, uid, data)
            pi = srv.layer.put_object_part(bucket, key, uid, part_num,
                                           data)
            root = ET.Element("CopyPartResult", xmlns=S3_NS)
            ET.SubElement(root, "ETag").text = f'"{pi.etag}"'
            ET.SubElement(root, "LastModified").text = \
                _iso_date(pi.mod_time or 0)
            self._send(200, _xml(root))

        def _lock_headers(self, bucket: str, key: str) -> dict[str, str]:
            """Explicit x-amz-object-lock-* headers, else the bucket's
            default retention (cmd/bucket-object-lock.go)."""
            from ..bucket import objectlock as olock
            raw = srv.bucket_meta.get_config(bucket, "object-lock")
            out: dict[str, str] = {}
            mode = self.headers.get(olock.AMZ_OBJECT_LOCK_MODE)
            until = self.headers.get(olock.AMZ_OBJECT_LOCK_RETAIN_UNTIL)
            hold = self.headers.get(olock.AMZ_OBJECT_LOCK_LEGAL_HOLD)
            if mode or until or hold:
                if raw is None:
                    raise S3Error("InvalidRequest")
                if (mode is None) != (until is None):
                    raise S3Error("InvalidRequest")
                if mode:
                    if mode not in (olock.GOVERNANCE, olock.COMPLIANCE):
                        raise S3Error("InvalidRequest")
                    # the retain-until header must be a valid, future
                    # timestamp — storing garbage would mint an object the
                    # client believes is WORM but that active() never locks
                    try:
                        dt = datetime.datetime.fromisoformat(
                            until.replace("Z", "+00:00"))
                        if dt.tzinfo is None:
                            dt = dt.replace(tzinfo=datetime.timezone.utc)
                    except ValueError as e:
                        raise S3Error("InvalidRequest") from e
                    if dt <= datetime.datetime.now(datetime.timezone.utc):
                        raise S3Error("InvalidRequest")
                    out[olock.AMZ_OBJECT_LOCK_MODE] = mode
                    out[olock.AMZ_OBJECT_LOCK_RETAIN_UNTIL] = \
                        dt.astimezone(datetime.timezone.utc).strftime(
                            "%Y-%m-%dT%H:%M:%SZ")
                if hold:
                    if hold not in ("ON", "OFF"):
                        raise S3Error("InvalidRequest")
                    out[olock.AMZ_OBJECT_LOCK_LEGAL_HOLD] = hold
                return out
            if raw is not None:
                cfg = _try(lambda: olock.LockConfig.parse(raw.encode()))
                out.update(cfg.default_retention_headers())
            return out

        def _get_object(self, bucket, key, query, head: bool):
            q1 = {k: v[0] for k, v in query.items()}
            vid = q1.get("versionId")
            if vid == "null":
                vid = ""
            opts = ol.ObjectOptions(version_id=vid)
            from ..crypto import sse as csse
            rng = self.headers.get("Range")
            offset, length = 0, -1
            sse_hdrs: dict[str, str] = {}
            plain_size: int | None = None
            from .. import compress as mtc
            try:
                oi_pre = None
                if any(h in self.headers for h in
                       ("If-Match", "If-None-Match", "If-Modified-Since",
                        "If-Unmodified-Since")):
                    # preconditions run on metadata BEFORE any data read
                    # — a 304 revalidation must not decode the object
                    oi_pre = srv.layer.get_object_info(bucket, key, opts)
                    if not oi_pre.delete_marker and \
                            self._preconditions_304(oi_pre):
                        return self._send(
                            304, b"",
                            headers={"ETag":
                                     f'"{self._display_etag(oi_pre)}"',
                                     "Last-Modified":
                                     _http_date(oi_pre.mod_time)},
                            content_length=0)
                body_gen = None    # streaming plain-object body
                if rng:
                    offset, length = _parse_range(rng)
                if head or rng:
                    # metadata first: a range is in client (decompressed/
                    # decrypted) space — fetching stored bytes at those
                    # offsets would decode data that gets thrown away
                    oi = oi_pre if oi_pre is not None else \
                        srv.layer.get_object_info(bucket, key, opts)
                    data = None
                    from ..objectlayer import tiering as _tchk
                    if rng and not head and \
                            _tchk.is_transitioned(oi.user_defined) and \
                            not _tchk.restore_valid(oi.user_defined):
                        # archived stub: 403 before the size-0 range
                        # fetch can 416
                        raise S3Error("InvalidObjectState")
                    if rng and not oi.delete_marker and \
                            mtc.META_COMPRESSION not in oi.user_defined \
                            and not csse.is_encrypted(oi.user_defined):
                        # plain ranged GET: only covering blocks are read
                        # and the body streams (erasure-decode.go:229-246)
                        oi, body_gen = srv.layer.get_object_reader(
                            bucket, key, offset, length, opts)
                else:
                    # full GET: reader returns metadata + a body stream;
                    # transform paths (SSE/compression) materialize below
                    oi, body_gen = srv.layer.get_object_reader(
                        bucket, key, 0, -1, opts)
                    data = None
                if not head and oi.delete_marker:
                    raise ol.MethodNotAllowed(key)
                from ..objectlayer import tiering
                archived = tiering.is_transitioned(oi.user_defined)
                stubbed = archived and \
                    not tiering.restore_valid(oi.user_defined)
                if stubbed and not head:
                    # data lives in the tier: GET needs a restore first
                    # (cmd/object-handlers.go InvalidObjectState)
                    raise S3Error("InvalidObjectState")
                encrypted = csse.is_encrypted(oi.user_defined) and \
                    not oi.delete_marker and not stubbed
                compressed = mtc.META_COMPRESSION in oi.user_defined and \
                    not oi.delete_marker and not stubbed
                if body_gen is not None and (encrypted or compressed):
                    # transform paths need the stored bytes in hand
                    data = b"".join(body_gen)
                    body_gen = None
                if stubbed:
                    # HEAD of the stub reports the archived identity
                    plain_size = int(oi.user_defined.get(
                        tiering.META_SIZE, "0"))
                inner: bytes | None = None
                if encrypted:
                    # DecryptObjectInfo: the data path reads only covering
                    # DARE packages (full stream when also compressed)
                    enc = csse.ObjectEncryption.open(
                        oi.user_defined, bucket, key, self.headers,
                        srv.kms)
                    inner_size = csse.decrypted_size(
                        oi.user_defined, oi.size, oi.parts)
                    sse_hdrs = csse.response_headers(oi.user_defined)
                    if not compressed:
                        plain_size = inner_size
                        if rng and offset >= plain_size:
                            raise S3Error("InvalidRange")
                    if not head:
                        if data is not None and not rng and \
                                len(data) == oi.size:
                            blob = data       # full ciphertext in hand

                            def read(o, n, _b=blob):
                                return _b[o:o + n]
                        else:
                            def read(o, n):
                                return srv.layer.get_object(
                                    bucket, key, o, n, opts)[1]
                        if compressed:
                            inner = csse.decrypt_object_range(
                                enc, oi.user_defined, oi.size, read,
                                0, -1, oi.parts)
                        else:
                            data = csse.decrypt_object_range(
                                enc, oi.user_defined, oi.size, read,
                                offset, length, oi.parts)
                if compressed:
                    if head:
                        plain_size = int(
                            oi.user_defined[csse.META_ACTUAL_SIZE])
                    else:
                        if inner is None:
                            if data is not None and not rng and \
                                    len(data) == oi.size:
                                inner = data
                            else:
                                _, inner = srv.layer.get_object(
                                    bucket, key, 0, -1, opts)
                        full = mtc.decompress_stream(inner)
                        plain_size = len(full)
                        if rng and offset >= plain_size:
                            raise S3Error("InvalidRange")
                        data = full[offset:] if length < 0 \
                            else full[offset:offset + length]
            except ol.MethodNotAllowed:
                # delete marker (cmd/object-handlers.go: 405 + header)
                return self._send(
                    405, s3err.to_xml(s3err.get("MethodNotAllowed")),
                    headers={"x-amz-delete-marker": "true"})
            entity_size = plain_size if plain_size is not None else oi.size
            hdrs = {
                "ETag": f'"{oi.etag}"',
                "Last-Modified": _http_date(oi.mod_time),
                "Accept-Ranges": "bytes",
            }
            if archived:
                from ..objectlayer import tiering as _tr
                hdrs["ETag"] = \
                    f'"{oi.user_defined.get(_tr.META_ETAG, oi.etag)}"'
                hdrs[_tr.STORAGE_CLASS_HDR] = oi.user_defined.get(
                    _tr.STORAGE_CLASS_HDR, "")
                rh = _tr.restore_header(oi.user_defined)
                if rh:
                    hdrs[_tr.RESTORE_HDR] = rh
            elif oi.user_defined.get("x-amz-storage-class"):
                # RRS objects report their class (AWS omits STANDARD)
                hdrs["x-amz-storage-class"] = \
                    oi.user_defined["x-amz-storage-class"]
            hdrs.update(sse_hdrs)
            if oi.version_id:
                hdrs["x-amz-version-id"] = oi.version_id
            for k2, v in oi.user_defined.items():
                if k2.startswith("x-amz-meta-"):
                    hdrs[k2] = v
            ct = oi.content_type or "binary/octet-stream"
            tag_hdr = oi.user_defined.get(self.TAG_KEY)
            if tag_hdr:
                hdrs["x-amz-tagging-count"] = str(
                    len(urllib.parse.parse_qsl(tag_hdr,
                                               keep_blank_values=True)))
            srv.notify("s3:ObjectAccessed:Head" if head
                       else "s3:ObjectAccessed:Get", bucket, oi)
            if head:
                if oi.delete_marker:
                    hdrs = {"x-amz-delete-marker": "true"}
                    if oi.version_id:
                        hdrs["x-amz-version-id"] = oi.version_id
                    return self._send(405, b"", headers=hdrs,
                                      content_length=0)
                return self._send(200, b"", content_type=ct, headers=hdrs,
                                  content_length=entity_size)
            if rng:
                if body_gen is not None:
                    start = max(0, entity_size + offset) if offset < 0 \
                        else offset
                    sent = entity_size - start if length < 0 \
                        else min(length, entity_size - start)
                    hdrs["Content-Range"] = \
                        f"bytes {start}-{start + sent - 1}/{entity_size}"
                    return self._send_stream(206, body_gen, sent, ct,
                                             hdrs)
                start = entity_size - len(data) if offset < 0 else offset
                hdrs["Content-Range"] = \
                    f"bytes {start}-{start + len(data) - 1}/{entity_size}"
                return self._send(206, data, content_type=ct, headers=hdrs)
            if body_gen is not None:
                return self._send_stream(200, body_gen, entity_size, ct,
                                         hdrs)
            return self._send(200, data, content_type=ct, headers=hdrs)

        def _storage_class_parity(self, user_defined: dict) -> int | None:
            """x-amz-storage-class -> parity override via the
            storage_class config subsystem (cmd/config/storageclass
            applied at cmd/erasure-object.go:631).  Also records RRS in
            metadata so HEAD reports it (AWS omits STANDARD)."""
            sc = self.headers.get("x-amz-storage-class", "").upper()
            explicit = sc not in ("", "STANDARD")
            if not explicit:
                value = srv.config.get("storage_class", "standard")
            elif sc == "REDUCED_REDUNDANCY":
                value = srv.config.get("storage_class", "rrs")
            else:
                raise S3Error("InvalidStorageClass")
            n = _layer_set_drive_count(srv.layer)
            if not value or not n:
                return None
            from ..utils.kvconfig import parse_storage_class
            try:
                parity = parse_storage_class(value, n)
            except ValueError as e:
                if explicit:
                    # the client asked for this class: tell them
                    raise S3Error("InvalidStorageClass") from e
                # bad *config* must not fail clients who sent no header
                return None
            if explicit:
                user_defined["x-amz-storage-class"] = sc
            return parity

        def _display_etag(self, oi) -> str:
            """The etag clients see: archived stubs advertise the
            original object's etag (META_ETAG), not the stub's."""
            from ..objectlayer import tiering as _tr
            if _tr.is_transitioned(oi.user_defined):
                return oi.user_defined.get(_tr.META_ETAG, oi.etag)
            return oi.etag

        def _preconditions_304(self, oi) -> bool:
            """Evaluate GET/HEAD preconditions (checkPreconditions,
            cmd/object-handlers-common.go).  Raises 412 for failed
            If-Match/If-Unmodified-Since; returns True when the response
            must be 304 Not Modified."""
            if_match = self.headers.get("If-Match")
            if_none = self.headers.get("If-None-Match")
            if_mod = self.headers.get("If-Modified-Since")
            if_unmod = self.headers.get("If-Unmodified-Since")
            etag = self._display_etag(oi)
            # Last-Modified is second-granularity: compare truncated
            # seconds or an echoed header spuriously fails
            mod_s = oi.mod_time // 10 ** 9

            def etag_in(header: str) -> bool:
                tags = [t.strip().strip('"') for t in header.split(",")]
                return "*" in tags or etag in tags

            def parse_date(v: str) -> float | None:
                try:
                    return email.utils.parsedate_to_datetime(v).timestamp()
                except (TypeError, ValueError):
                    return None         # invalid dates are ignored

            if if_match is not None and not etag_in(if_match):
                raise S3Error("PreconditionFailed")
            if if_match is None and if_unmod is not None:
                t = parse_date(if_unmod)
                if t is not None and mod_s > t:
                    raise S3Error("PreconditionFailed")
            if if_none is not None and etag_in(if_none):
                return True
            if if_none is None and if_mod is not None:
                t = parse_date(if_mod)
                if t is not None and mod_s <= t:
                    return True
            return False

        def _restore_object(self, bucket, key, query, payload):
            """PostRestoreObjectHandler: <RestoreRequest><Days>N</Days>
            </RestoreRequest> copies tiered bytes back for N days."""
            from ..objectlayer import tiering
            days = 1
            if payload:
                try:
                    root = ET.fromstring(payload)
                    for el in root.iter():
                        if el.tag.split("}")[-1] == "Days":
                            days = int(el.text or 1)
                except (ET.ParseError, ValueError) as e:
                    raise S3Error("MalformedXML") from e
            if days < 1:
                raise S3Error("InvalidArgument")
            vid = query.get("versionId", [None])[0]
            if vid == "null":
                vid = ""                # explicit null version
            ts = srv.transition
            try:
                fresh = ts.restore(bucket, key, days, version_id=vid)
            except tiering.TierError as e:
                # only "not archived" is the client's mistake; a tier
                # backend failure is a server-side problem, not a 403
                if "archived state" in str(e):
                    raise S3Error("InvalidObjectState") from e
                raise S3Error("InternalError") from e
            oi = srv.layer.get_object_info(
                bucket, key, ol.ObjectOptions(version_id=vid))
            srv.notify("s3:ObjectRestore:Completed", bucket, oi)
            # 202 while "in progress" (fresh copy), 200 when it already
            # held a valid restored copy (object-handlers.go semantics)
            return self._send(202 if fresh else 200, b"")

        def _tiered_meta_of(self, bucket, key, vid, versioned):
            """Metadata of the version about to be removed/replaced, for
            freeing its tier bytes AFTER the destructive op commits.
            None when nothing tiered is at stake.  vid semantics follow
            the layer: None = latest, "" = null version."""
            if not srv.transition.tiers:
                return None
            if versioned and vid is None:
                return None         # delete-marker write keeps the data
            try:
                old = srv.layer.get_object_info(
                    bucket, key, ol.ObjectOptions(version_id=vid))
            except ol.ObjectLayerError:
                return None
            from ..objectlayer import tiering as _tr
            return old.user_defined \
                if _tr.is_transitioned(old.user_defined) else None

        def _delete_object(self, bucket, key, query):
            q1 = {k: v[0] for k, v in query.items()}
            vid = q1.get("versionId")
            if vid == "null":
                vid = ""
            self._check_retention(bucket, key, vid)
            versioned = srv.bucket_meta.versioning_enabled(bucket)
            tiered_ud = self._tiered_meta_of(bucket, key, vid, versioned)
            res = srv.layer.delete_object(
                bucket, key, ol.ObjectOptions(version_id=vid,
                                              versioned=versioned))
            if tiered_ud is not None:   # freed only after the commit
                srv.transition.delete_tiered(tiered_ud)
            hdrs = {}
            if res.delete_marker:
                hdrs["x-amz-delete-marker"] = "true"
            if res.version_id:
                hdrs["x-amz-version-id"] = res.version_id
            srv.notify("s3:ObjectRemoved:DeleteMarkerCreated"
                       if res.delete_marker else "s3:ObjectRemoved:Delete",
                       bucket, res)
            srv.replicate(bucket, res, delete=True)
            self._send(204, headers=hdrs)

        def _check_retention(self, bucket, key, vid) -> None:
            """WORM enforcement: deleting a *specific version* under
            retention/legal hold is refused (a versioned delete that only
            writes a delete marker is always allowed)."""
            from ..bucket import objectlock as olock
            if vid is None:
                if srv.bucket_meta.versioning_enabled(bucket):
                    return      # becomes a delete marker, data retained
            if srv.bucket_meta.get_config(bucket, "object-lock") is None:
                return
            try:
                oi = srv.layer.get_object_info(
                    bucket, key, ol.ObjectOptions(version_id=vid))
            except ol.ObjectLayerError:
                return
            bypass = self._governance_bypass(f"{bucket}/{key}")
            if not olock.check_delete_allowed(oi.user_defined,
                                              governance_bypass=bypass):
                raise S3Error("ObjectLocked")

    return Handler


def _actual_size(oi) -> int:
    """Client-visible size (GetActualSize, cmd/object-api-utils.go): the
    pre-compression size for compressed objects, the DARE-plaintext size
    for encrypted-only objects, else the stored size."""
    from ..crypto import sse as csse
    raw = oi.user_defined.get(csse.META_ACTUAL_SIZE)
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    if csse.is_encrypted(oi.user_defined):
        try:
            return csse.decrypted_size(oi.user_defined, oi.size, oi.parts)
        except Exception:  # noqa: BLE001 — corrupt meta: report stored size
            pass
    return oi.size


def _parse_range(spec: str) -> tuple[int, int]:
    """HTTP Range -> (offset, length) without knowing the size
    (cmd/httprange.go); negative offset = suffix, length -1 = to-end.
    Size-dependent validation/clamping happens in the object layer, so a
    ranged GET costs a single quorum metadata read."""
    m = re.match(r"^bytes=(\d*)-(\d*)$", spec.strip())
    if not m:
        raise S3Error("InvalidRange")
    first, last = m.group(1), m.group(2)
    if first == "" and last == "":
        raise S3Error("InvalidRange")
    if first == "":  # suffix range: last N bytes
        n = int(last)
        if n == 0:
            raise S3Error("InvalidRange")
        return -n, -1
    start = int(first)
    if last == "":
        return start, -1
    end = int(last)
    if end < start:
        raise S3Error("InvalidRange")
    return start, end - start + 1
