"""AWS Signature Version 4 — signer and verifier.

Reference behavior: cmd/signature-v4.go:331 (doesSignatureMatch),
presigned :205 (doesPresignedSignatureMatch).  Both the server-side
verification and a client-side signer (used by our own S3 client, the
replication worker, and the test suite) share one canonicalization.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass

ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
ISO8601 = "%Y%m%dT%H%M%SZ"

# default presign expiry limit (7 days, AWS parity)
MAX_PRESIGN_EXPIRES = 7 * 24 * 3600


class SigV4Error(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-._~" if encode_slash else "-._~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query(query: dict[str, list[str]],
                    drop: set[str] = frozenset()) -> str:
    pairs = []
    for key in sorted(query):
        if key in drop:
            continue
        for v in sorted(query[key]):
            pairs.append(f"{_uri_encode(key)}={_uri_encode(v)}")
    return "&".join(pairs)


def canonical_request(method: str, path: str, query: dict[str, list[str]],
                      headers: dict[str, str], signed_headers: list[str],
                      payload_hash: str,
                      drop_query: set[str] = frozenset()) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers)
    return "\n".join([
        method.upper(),
        _uri_encode(path, encode_slash=False) or "/",
        canonical_query(query, drop_query),
        canon_headers,
        ";".join(signed_headers),
        payload_hash,
    ])


def signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = hmac.new(f"AWS4{secret}".encode(), date.encode(),
                 hashlib.sha256).digest()
    for part in (region, service, "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    return k


def string_to_sign(timestamp: str, scope: str, canonical: str) -> str:
    return "\n".join([
        ALGORITHM, timestamp, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])


@dataclass
class Credentials:
    access_key: str
    secret_key: str


# ---------------------------------------------------------------------------
# client-side signer
# ---------------------------------------------------------------------------

def sign_request(creds: Credentials, method: str, url: str,
                 headers: dict[str, str], payload: bytes = b"",
                 region: str = "us-east-1", service: str = "s3",
                 timestamp: datetime.datetime | None = None
                 ) -> dict[str, str]:
    """Sign; returns the full header set to send (signed-payload mode)."""
    u = urllib.parse.urlsplit(url)
    # the wire path may be %-encoded; canonicalize from the DECODED path
    # (matching the server, which unquotes before re-encoding)
    path = urllib.parse.unquote(u.path)
    query = urllib.parse.parse_qs(u.query, keep_blank_values=True)
    ts = timestamp or datetime.datetime.now(datetime.timezone.utc)
    amz_date = ts.strftime(ISO8601)
    date = amz_date[:8]
    payload_hash = hashlib.sha256(payload).hexdigest()
    out = dict(headers)
    out["host"] = u.netloc
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_hash
    signed = sorted(h.lower() for h in out)
    scope = f"{date}/{region}/{service}/aws4_request"
    canon = canonical_request(method, path or "/", query,
                              {k.lower(): v for k, v in out.items()},
                              signed, payload_hash)
    sts = string_to_sign(amz_date, scope, canon)
    sig = hmac.new(signing_key(creds.secret_key, date, region, service),
                   sts.encode(), hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"{ALGORITHM} Credential={creds.access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return out


def sign_request_streaming(creds: Credentials, method: str, url: str,
                           headers: dict[str, str], payload: bytes,
                           chunk_size: int = 64 * 1024,
                           region: str = "us-east-1",
                           timestamp: datetime.datetime | None = None
                           ) -> tuple[dict[str, str], bytes]:
    """Client-side aws-chunked upload: returns (headers, framed_body).
    Mirrors what aws SDKs send for STREAMING-AWS4-HMAC-SHA256-PAYLOAD."""
    u = urllib.parse.urlsplit(url)
    upath = urllib.parse.unquote(u.path)
    query = urllib.parse.parse_qs(u.query, keep_blank_values=True)
    ts = timestamp or datetime.datetime.now(datetime.timezone.utc)
    amz_date = ts.strftime(ISO8601)
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    out = dict(headers)
    out["host"] = u.netloc
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = STREAMING_PAYLOAD
    out["content-encoding"] = "aws-chunked"
    out["x-amz-decoded-content-length"] = str(len(payload))
    signed = sorted(h.lower() for h in out)
    canon = canonical_request(method, upath or "/", query,
                              {k.lower(): v for k, v in out.items()},
                              signed, STREAMING_PAYLOAD)
    sts = string_to_sign(amz_date, scope, canon)
    key = signing_key(creds.secret_key, date, region, "s3")
    seed = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"{ALGORITHM} Credential={creds.access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={seed}")
    body = bytearray()
    prev = seed
    chunks = [payload[i:i + chunk_size]
              for i in range(0, len(payload), chunk_size)] + [b""]
    for chunk in chunks:
        csts = "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev,
            EMPTY_SHA256, hashlib.sha256(chunk).hexdigest()])
        sig = hmac.new(key, csts.encode(), hashlib.sha256).hexdigest()
        body += f"{len(chunk):x};chunk-signature={sig}\r\n".encode()
        body += chunk + b"\r\n"
        prev = sig
    return out, bytes(body)


def presign_url(creds: Credentials, method: str, url: str,
                expires: int = 3600, region: str = "us-east-1",
                timestamp: datetime.datetime | None = None) -> str:
    """Generate a presigned URL (query-string auth)."""
    u = urllib.parse.urlsplit(url)
    path = urllib.parse.unquote(u.path)
    query = urllib.parse.parse_qs(u.query, keep_blank_values=True)
    ts = timestamp or datetime.datetime.now(datetime.timezone.utc)
    amz_date = ts.strftime(ISO8601)
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    query.update({
        "X-Amz-Algorithm": [ALGORITHM],
        "X-Amz-Credential": [f"{creds.access_key}/{scope}"],
        "X-Amz-Date": [amz_date],
        "X-Amz-Expires": [str(expires)],
        "X-Amz-SignedHeaders": ["host"],
    })
    canon = canonical_request(method, path or "/", query,
                              {"host": u.netloc}, ["host"],
                              UNSIGNED_PAYLOAD)
    sts = string_to_sign(amz_date, scope, canon)
    sig = hmac.new(signing_key(creds.secret_key, date, region, "s3"),
                   sts.encode(), hashlib.sha256).hexdigest()
    query["X-Amz-Signature"] = [sig]
    qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
    return urllib.parse.urlunsplit(
        (u.scheme, u.netloc, u.path, qs, ""))


# ---------------------------------------------------------------------------
# server-side verifier
# ---------------------------------------------------------------------------

def _parse_auth_header(auth: str) -> tuple[str, str, list[str], str]:
    """-> (access_key, scope, signed_headers, signature)."""
    if not auth.startswith(ALGORITHM):
        raise SigV4Error("AccessDenied", "unsupported algorithm")
    fields = {}
    for part in auth[len(ALGORITHM):].strip().split(","):
        if "=" not in part:
            raise SigV4Error("AuthorizationHeaderMalformed", part)
        k, v = part.strip().split("=", 1)
        fields[k] = v
    try:
        cred = fields["Credential"]
        signed = fields["SignedHeaders"].split(";")
        sig = fields["Signature"]
    except KeyError as e:
        raise SigV4Error("AuthorizationHeaderMalformed", str(e)) from e
    if "/" not in cred:
        raise SigV4Error("AuthorizationHeaderMalformed", cred)
    access_key, scope = cred.split("/", 1)
    return access_key, scope, signed, sig


def verify_request(lookup_secret, method: str, path: str,
                   query: dict[str, list[str]], headers: dict[str, str],
                   payload_hash: str,
                   region: str = "us-east-1",
                   now: datetime.datetime | None = None) -> str:
    """Verify a header-signed request; returns the access key.

    ``lookup_secret(access_key) -> secret | None``.
    Mirrors doesSignatureMatch (cmd/signature-v4.go:331).
    """
    headers = {k.lower(): v for k, v in headers.items()}
    auth = headers.get("authorization", "")
    if not auth:
        raise SigV4Error("AccessDenied", "missing Authorization")
    access_key, scope, signed, got_sig = _parse_auth_header(auth)
    parts = scope.split("/")
    if len(parts) != 4 or parts[3] != "aws4_request" or parts[2] != "s3":
        raise SigV4Error("AuthorizationHeaderMalformed", scope)
    date, req_region = parts[0], parts[1]
    if req_region != region:
        raise SigV4Error("AuthorizationHeaderMalformed",
                         f"wrong region {req_region}")
    secret = lookup_secret(access_key)
    if secret is None:
        raise SigV4Error("InvalidAccessKeyId", access_key)
    amz_date = headers.get("x-amz-date") or headers.get("date", "")
    if not amz_date:
        raise SigV4Error("AccessDenied", "missing date")
    # clock skew check (15 min, AWS parity)
    try:
        req_time = datetime.datetime.strptime(amz_date, ISO8601).replace(
            tzinfo=datetime.timezone.utc)
    except ValueError as e:
        raise SigV4Error("AccessDenied", "malformed date") from e
    now = now or datetime.datetime.now(datetime.timezone.utc)
    if abs((now - req_time).total_seconds()) > 15 * 60:
        raise SigV4Error("RequestTimeTooSkewed", amz_date)
    if "host" not in signed:
        raise SigV4Error("AccessDenied", "host header not signed")
    canon = canonical_request(method, path, query, headers, signed,
                              payload_hash)
    sts = string_to_sign(amz_date, scope, canon)
    want = hmac.new(signing_key(secret, date, region, "s3"),
                    sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, got_sig):
        raise SigV4Error("SignatureDoesNotMatch", "signature mismatch")
    return access_key


def verify_request_streaming(lookup_secret, method: str, path: str,
                             query: dict[str, list[str]],
                             headers: dict[str, str],
                             region: str = "us-east-1",
                             now: datetime.datetime | None = None
                             ) -> tuple[str, bytes, str, str, str]:
    """Verify the seed request of an aws-chunked upload; returns
    (access_key, signing_key, seed_signature, amz_date, scope) for the
    per-chunk chain (cmd/streaming-signature-v4.go:40)."""
    access_key = verify_request(lookup_secret, method, path, query, headers,
                                STREAMING_PAYLOAD, region, now)
    hl = {k.lower(): v for k, v in headers.items()}
    _, scope, _, seed_sig = _parse_auth_header(hl["authorization"])
    date = scope.split("/")[0]
    key = signing_key(lookup_secret(access_key), date, region, "s3")
    return access_key, key, seed_sig, hl.get("x-amz-date", ""), scope


def decode_chunked_payload(body: bytes, key: bytes, seed_signature: str,
                           amz_date: str, scope: str) -> bytes:
    """Decode and verify STREAMING-AWS4-HMAC-SHA256-PAYLOAD framing
    (cmd/streaming-signature-v4.go:156 newSignV4ChunkedReader).

    Each chunk: ``<hex-size>;chunk-signature=<sig>\\r\\n<data>\\r\\n``;
    chain: sig_n over (prev_sig, sha256(chunk_n)); final chunk size 0.
    """
    out = bytearray()
    prev = seed_signature
    pos = 0
    while True:
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            raise SigV4Error("IncompleteBody", "missing chunk header")
        header = body[pos:nl].decode("ascii", "replace")
        if ";chunk-signature=" not in header:
            raise SigV4Error("SignatureDoesNotMatch", "bad chunk header")
        size_hex, sig = header.split(";chunk-signature=", 1)
        try:
            size = int(size_hex, 16)
        except ValueError as e:
            raise SigV4Error("IncompleteBody", "bad chunk size") from e
        data = body[nl + 2: nl + 2 + size]
        if len(data) != size:
            raise SigV4Error("IncompleteBody", "short chunk")
        sts = "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev,
            EMPTY_SHA256, hashlib.sha256(data).hexdigest()])
        want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            raise SigV4Error("SignatureDoesNotMatch",
                             f"chunk signature mismatch at {pos}")
        prev = want
        pos = nl + 2 + size + 2  # skip trailing \r\n
        if size == 0:
            break
        out += data
    return bytes(out)


class ChunkedStreamReader:
    """Incremental STREAMING-AWS4-HMAC-SHA256-PAYLOAD decoder
    (cmd/streaming-signature-v4.go:156 newSignV4ChunkedReader): reads the
    framed body from ``raw`` (file-like with .readline/.read), verifies
    each chunk's signature chain, and exposes plain .read(n) so a 5 GiB
    aws-chunked PUT streams without buffering."""

    MAX_CHUNK_SIZE = 16 * 1024 * 1024   # maxChunkSize guard: one declared
    # chunk must never force a multi-GiB buffer before its signature check

    def __init__(self, raw, key: bytes, seed_signature: str,
                 amz_date: str, scope: str):
        self.raw = raw
        self.key = key
        self.prev = seed_signature
        self.amz_date = amz_date
        self.scope = scope
        self.buf = bytearray()
        self.done = False

    def _next_chunk(self) -> bytes:
        line = self.raw.readline(8192)
        if not line.endswith(b"\r\n"):
            raise SigV4Error("IncompleteBody", "missing chunk header")
        header = line[:-2].decode("ascii", "replace")
        if ";chunk-signature=" not in header:
            raise SigV4Error("SignatureDoesNotMatch", "bad chunk header")
        size_hex, sig = header.split(";chunk-signature=", 1)
        try:
            size = int(size_hex, 16)
        except ValueError as e:
            raise SigV4Error("IncompleteBody", "bad chunk size") from e
        if size > self.MAX_CHUNK_SIZE:
            raise SigV4Error("InvalidRequest",
                             f"chunk size {size} exceeds maximum")
        chunks = []
        remaining = size
        while remaining > 0:
            c = self.raw.read(remaining)
            if not c:
                raise SigV4Error("IncompleteBody", "short chunk")
            chunks.append(c)
            remaining -= len(c)
        data = b"".join(chunks)
        if self.raw.read(2) != b"\r\n":
            raise SigV4Error("IncompleteBody", "missing chunk trailer")
        sts = "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD", self.amz_date, self.scope,
            self.prev, EMPTY_SHA256, hashlib.sha256(data).hexdigest()])
        want = hmac.new(self.key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            raise SigV4Error("SignatureDoesNotMatch",
                             "chunk signature mismatch")
        self.prev = want
        if size == 0:
            self.done = True
        return data

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            while not self.done:
                self.buf += self._next_chunk()
            out = bytes(self.buf)
            self.buf = bytearray()
            return out
        while len(self.buf) < n and not self.done:
            self.buf += self._next_chunk()
        out = bytes(self.buf[:n])
        del self.buf[:n]
        return out


def verify_presigned(lookup_secret, method: str, path: str,
                     query: dict[str, list[str]], headers: dict[str, str],
                     region: str = "us-east-1",
                     now: datetime.datetime | None = None) -> str:
    """Verify query-string (presigned) auth; returns the access key.
    Mirrors doesPresignedSignatureMatch (cmd/signature-v4.go:205)."""
    q1 = {k: v[0] for k, v in query.items()}
    try:
        if q1["X-Amz-Algorithm"] != ALGORITHM:
            raise SigV4Error("AccessDenied", "bad algorithm")
        cred = q1["X-Amz-Credential"]
        amz_date = q1["X-Amz-Date"]
        expires = int(q1["X-Amz-Expires"])
        signed = q1["X-Amz-SignedHeaders"].split(";")
        got_sig = q1["X-Amz-Signature"]
    except (KeyError, ValueError) as e:
        raise SigV4Error("AuthorizationQueryParametersError", str(e)) from e
    access_key, scope = cred.split("/", 1)
    date, req_region = scope.split("/")[0:2]
    if req_region != region:
        raise SigV4Error("AuthorizationQueryParametersError", req_region)
    if not 1 <= expires <= MAX_PRESIGN_EXPIRES:
        raise SigV4Error("AuthorizationQueryParametersError",
                         "invalid expires")
    secret = lookup_secret(access_key)
    if secret is None:
        raise SigV4Error("InvalidAccessKeyId", access_key)
    try:
        req_time = datetime.datetime.strptime(amz_date, ISO8601).replace(
            tzinfo=datetime.timezone.utc)
    except ValueError as e:
        raise SigV4Error("AuthorizationQueryParametersError",
                         "malformed X-Amz-Date") from e
    now = now or datetime.datetime.now(datetime.timezone.utc)
    if now < req_time - datetime.timedelta(minutes=15):
        raise SigV4Error("RequestTimeTooSkewed", amz_date)
    if (now - req_time).total_seconds() > expires:
        raise SigV4Error("ExpiredToken", "request has expired")
    headers = {k.lower(): v for k, v in headers.items()}
    canon = canonical_request(method, path, query, headers, signed,
                              q1.get("X-Amz-Content-Sha256",
                                     UNSIGNED_PAYLOAD),
                              drop_query={"X-Amz-Signature"})
    sts = string_to_sign(amz_date, scope, canon)
    want = hmac.new(signing_key(secret, date, region, "s3"),
                    sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, got_sig):
        raise SigV4Error("SignatureDoesNotMatch", "signature mismatch")
    return access_key
