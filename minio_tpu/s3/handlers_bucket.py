"""S3 bucket-level handlers (cmd/bucket-handlers.go, cmd/bucket-*-handlers.go).

Extracted from s3/server.py (round-3 split: the 2800-line monolith
became core plumbing + per-family handler modules with NO behavior
change).  Functions here are attached to the request-handler class by
_make_handler (server.py); ``self`` is the handler instance and
``self.srv`` the owning S3Server.
"""

from __future__ import annotations

import urllib.parse
import xml.etree.ElementTree as ET

from ..iam import policy as iampol
from ..objectlayer import interface as ol
from . import errors as s3err
from . import sigv4
from .server import (MAX_OBJECT_SIZE, S3_NS, S3Error, _actual_size,
                     _canned_acl_xml, _iso_date, _try, _xml)

def _list_buckets(self):
    if self.command != "GET":
        raise S3Error("MethodNotAllowed")
    self._allow(iampol.LIST_ALL_MY_BUCKETS)
    root = ET.Element("ListAllMyBucketsResult", xmlns=S3_NS)
    owner = ET.SubElement(root, "Owner")
    ET.SubElement(owner, "ID").text = "minio-tpu"
    ET.SubElement(owner, "DisplayName").text = "minio-tpu"
    buckets = ET.SubElement(root, "Buckets")
    for b in self.srv.layer.list_buckets():
        be = ET.SubElement(buckets, "Bucket")
        ET.SubElement(be, "Name").text = b.name
        ET.SubElement(be, "CreationDate").text = _iso_date(b.created)
    self._send(200, _xml(root))

# config subresources: query-param -> (module handler); each stores
# the raw document in BucketMetadataSys and round-trips it on GET
# (cmd/bucket-handlers.go, cmd/bucket-lifecycle-handlers.go, ...)

def _config_api(self, bucket, query, payload) -> bool:
    from ..bucket import (encryption, lifecycle, notification,
                          objectlock, replication, tags)
    from ..bucket import policy as bpolicy
    cmd = self.command
    if not ({"policy", "lifecycle", "encryption", "replication",
             "notification", "object-lock", "tagging", "quota",
             "acl", "cors", "website", "accelerate",
             "requestPayment", "logging"} & set(query)):
        return False

    def exists():
        # authorization happens BEFORE the existence check so an
        # unauthenticated caller cannot enumerate bucket names by
        # distinguishing 404 from 403 (cmd/auth-handler.go order)
        self.srv.layer.get_bucket_info(bucket)

    def crud(param, get_act, put_act, parse, not_found,
             store_key=None, deletable=True, parse_err="MalformedXML"):
        if param not in query:
            return False
        store_key = store_key or param
        if cmd == "PUT":
            self._allow(put_act, bucket)
            exists()
            try:
                doc = parse(payload)
            except (ValueError, KeyError) as e:
                code = getattr(e, "code", parse_err)
                raise S3Error(code) from e
            self.srv.bucket_meta.set_config(bucket, store_key, doc)
            self._send(200)
        elif cmd == "GET":
            self._allow(get_act, bucket)
            exists()
            raw = self.srv.bucket_meta.get_config(bucket, store_key)
            if raw is None:
                raise S3Error(not_found)
            ctype = "application/json" \
                if store_key == "policy" else "application/xml"
            self._send(200, raw.encode(), content_type=ctype)
        elif cmd == "DELETE" and deletable:
            self._allow(put_act, bucket)
            exists()
            self.srv.bucket_meta.set_config(bucket, store_key, None)
            self._send(204)
        else:
            raise S3Error("MethodNotAllowed")
        return True

    # dummy sub-resources (cmd/dummy-handlers.go): authorize with
    # the bucket-policy action, validate existence, then return
    # the fixed default (or the documented error); DELETE website
    # succeeds as a no-op
    _DUMMY = {
        "accelerate": (
            b'<?xml version="1.0" encoding="UTF-8"?>'
            b'<AccelerateConfiguration xmlns="http://s3.amazonaws'
            b'.com/doc/2006-03-01/"/>'),
        "requestPayment": (
            b'<?xml version="1.0" encoding="UTF-8"?>'
            b'<RequestPaymentConfiguration xmlns="http://s3.'
            b'amazonaws.com/doc/2006-03-01/"><Payer>BucketOwner'
            b'</Payer></RequestPaymentConfiguration>'),
        "logging": (
            b'<?xml version="1.0" encoding="UTF-8"?>'
            b'<BucketLoggingStatus xmlns="http://s3.amazonaws.com'
            b'/doc/2006-03-01/"></BucketLoggingStatus>'),
        "website": None,     # GET -> NoSuchWebsiteConfiguration
    }
    for param, body in _DUMMY.items():
        if param not in query:
            continue
        self._allow(iampol.GET_BUCKET_POLICY, bucket)
        exists()
        if param == "website" and cmd == "DELETE":
            self._send(204)
        elif cmd == "GET":
            if body is None:
                raise S3Error("NoSuchWebsiteConfiguration")
            self._send(200, body,
                       content_type="application/xml")
        else:
            raise S3Error("NotImplemented")
        return True

    if crud("policy", iampol.GET_BUCKET_POLICY,
            iampol.PUT_BUCKET_POLICY,
            lambda p: bpolicy.BucketPolicy.parse(p, bucket)
            .to_json().decode(),
            "NoSuchBucketPolicy", parse_err="MalformedPolicy"):
        return True
    if crud("lifecycle", iampol.GET_LIFECYCLE, iampol.PUT_LIFECYCLE,
            lambda p: lifecycle.Lifecycle.parse(p).to_xml().decode(),
            "NoSuchLifecycleConfiguration"):
        return True
    if crud("encryption", iampol.GET_BUCKET_ENCRYPTION,
            iampol.PUT_BUCKET_ENCRYPTION,
            lambda p: encryption.SSEConfig.parse(p)
            .to_xml().decode(),
            "ServerSideEncryptionConfigurationNotFoundError"):
        return True
    if "replication" in query and cmd == "PUT":
        # destination ARN must name a registered remote target
        self._allow(iampol.PUT_REPLICATION, bucket)
        exists()
        cfg = _try(lambda: replication.Config.parse(payload))
        if not self.srv.bucket_meta.versioning_enabled(bucket):
            raise S3Error("InvalidRequest")
        if self.srv.replication is not None:
            for r in cfg.rules:
                if not self.srv.replication.arn_exists(
                        r.destination_arn):
                    raise S3Error(
                        "ReplicationDestinationNotFoundError")
        self.srv.bucket_meta.set_config(bucket, "replication",
                                   cfg.to_xml().decode())
        return self._send(200) or True
    if crud("replication", iampol.GET_REPLICATION,
            iampol.PUT_REPLICATION,
            lambda p: replication.Config.parse(p).to_xml().decode(),
            "ReplicationConfigurationNotFoundError"):
        return True
    if "notification" in query:
        if cmd == "PUT":
            self._allow(iampol.PUT_BUCKET_NOTIFICATION, bucket)
            exists()
            cfg = _try(lambda: notification.Config.parse(
                payload, valid_arns=self.srv.events.valid_arns()))
            self.srv.bucket_meta.set_config(
                bucket, "notification",
                cfg.to_xml().decode() if cfg.targets else None)
            return self._send(200) or True
        if cmd == "GET":
            self._allow(iampol.GET_BUCKET_NOTIFICATION, bucket)
            exists()
            raw = self.srv.bucket_meta.get_config(bucket, "notification")
            if raw is None:
                raw = notification.Config().to_xml().decode()
            return self._send(200, raw.encode()) or True
        raise S3Error("MethodNotAllowed")
    if "object-lock" in query:
        if cmd == "PUT":
            self._allow(iampol.PUT_BUCKET_OBJECT_LOCK, bucket)
            exists()
            cfg = _try(lambda: objectlock.LockConfig.parse(payload))
            if self.srv.bucket_meta.get_config(bucket,
                                          "object-lock") is None:
                # can only be set at creation in S3; MinIO allows
                # updating the default rule iff lock was enabled
                raise S3Error(
                    "InvalidBucketObjectLockConfiguration")
            self.srv.bucket_meta.set_config(bucket, "object-lock",
                                       cfg.to_xml().decode())
            return self._send(200) or True
        if cmd == "GET":
            self._allow(iampol.GET_BUCKET_OBJECT_LOCK, bucket)
            exists()
            raw = self.srv.bucket_meta.get_config(bucket, "object-lock")
            if raw is None:
                raise S3Error(
                    "ObjectLockConfigurationNotFoundError")
            return self._send(200, raw.encode()) or True
        raise S3Error("MethodNotAllowed")
    if "tagging" in query:
        if cmd == "PUT":
            self._allow(iampol.PUT_BUCKET_TAGGING, bucket)
            exists()
            t = _try(lambda: tags.parse_xml(payload,
                                            is_object=False))
            self.srv.bucket_meta.set_config(bucket, "tagging",
                                       tags.to_xml(t).decode())
            return self._send(200) or True
        if cmd == "GET":
            self._allow(iampol.GET_BUCKET_TAGGING, bucket)
            exists()
            raw = self.srv.bucket_meta.get_config(bucket, "tagging")
            if raw is None:
                raise S3Error("NoSuchTagSet")
            return self._send(200, raw.encode()) or True
        if cmd == "DELETE":
            self._allow(iampol.PUT_BUCKET_TAGGING, bucket)
            exists()
            self.srv.bucket_meta.set_config(bucket, "tagging", None)
            return self._send(204) or True
        raise S3Error("MethodNotAllowed")
    if "quota" in query:  # admin-style; also exposed here
        from ..bucket.quota import Quota
        if cmd == "PUT":
            self._allow(iampol.ADMIN_ALL, bucket)
            exists()
            q = _try(lambda: Quota.parse(payload))
            self.srv.bucket_meta.set_config(bucket, "quota",
                                       q.to_json().decode())
            return self._send(200) or True
        if cmd == "GET":
            self._allow(iampol.ADMIN_ALL, bucket)
            exists()
            raw = self.srv.bucket_meta.get_config(bucket, "quota") \
                or '{"quota": 0, "quotatype": "hard"}'
            return self._send(200, raw.encode(),
                              content_type="application/json") \
                or True
        raise S3Error("MethodNotAllowed")
    if "acl" in query:
        if cmd == "GET":
            self._allow(iampol.GET_BUCKET_ACL, bucket)
            exists()
            return self._send(200, _canned_acl_xml()) or True
        if cmd == "PUT":
            # only the private canned ACL is accepted
            self._allow(iampol.PUT_BUCKET_ACL, bucket)
            exists()
            acl = self.headers.get("x-amz-acl", "private")
            if acl != "private" or (payload and
                                    b"FULL_CONTROL" not in payload):
                raise S3Error("NotImplemented")
            return self._send(200) or True
        raise S3Error("MethodNotAllowed")
    if "cors" in query:
        self._allow(iampol.GET_BUCKET_LOCATION, bucket)
        exists()
        if cmd == "GET":
            raise S3Error("NoSuchCORSConfiguration")
        raise S3Error("NotImplemented")
    return False

def _bucket_api(self, bucket, query, payload):
    cmd = self.command
    if self._config_api(bucket, query, payload):
        return
    if cmd == "PUT" and "versioning" in query:
        self._allow(iampol.PUT_BUCKET_VERSIONING, bucket)
        return self._put_versioning(bucket, payload)
    if cmd == "GET" and "versioning" in query:
        self._allow(iampol.GET_BUCKET_VERSIONING, bucket)
        return self._get_versioning(bucket)
    if cmd == "GET" and "location" in query:
        self._allow(iampol.GET_BUCKET_LOCATION, bucket)
        root = ET.Element("LocationConstraint", xmlns=S3_NS)
        # us-east-1 is the EMPTY constraint on the wire (AWS contract;
        # cmd/api-response.go LocationResponse) — clients special-case it
        root.text = "" if self.srv.region == "us-east-1" \
            else self.srv.region
        self.srv.layer.get_bucket_info(bucket)
        return self._send(200, _xml(root))
    if cmd == "GET" and "versions" in query:
        self._allow(iampol.LIST_BUCKET_VERSIONS, bucket)
        return self._list_object_versions(bucket, query)
    if cmd == "GET" and "events" in query:
        self._allow(iampol.LISTEN_NOTIFICATION, bucket)
        return self._listen_notification(bucket, query)
    if cmd == "POST" and "delete" in query:
        return self._delete_objects(bucket, payload)
    if cmd == "POST" and (self.headers.get("Content-Type") or ""
                          ).startswith("multipart/form-data"):
        return self._post_policy_upload(bucket, payload)
    if cmd == "GET" and "uploads" in query:
        self._allow(iampol.LIST_MULTIPART_UPLOADS, bucket)
        return self._list_uploads(bucket, query)
    if cmd == "PUT":
        self._allow(iampol.CREATE_BUCKET, bucket)
        fresh_rec = False
        if self.srv.federation is not None:
            from ..utils.fed_dns import BucketTaken
            try:
                fresh_rec = self.srv.federation.register(bucket)
            except BucketTaken:
                raise S3Error("BucketAlreadyExists") from None
        try:
            self.srv.layer.make_bucket(bucket)
        except Exception:
            if self.srv.federation is not None and fresh_rec:
                self.srv.federation.unregister(bucket)
            raise
        if self.headers.get("x-amz-bucket-object-lock-enabled",
                            "").lower() == "true":
            # lock implies versioning (cmd/bucket-handlers.go
            # PutBucketHandler: object-lock buckets are versioned)
            from ..bucket.objectlock import LockConfig
            self.srv.bucket_meta.set_versioning(bucket, True)
            self.srv.bucket_meta.set_config(
                bucket, "object-lock",
                LockConfig(enabled=True).to_xml().decode())
        return self._send(200, headers={"Location": f"/{bucket}"})
    if cmd == "HEAD":
        self._allow(iampol.LIST_BUCKET, bucket)
        self.srv.layer.get_bucket_info(bucket)
        return self._send(200)
    if cmd == "DELETE":
        self._allow(iampol.DELETE_BUCKET, bucket)
        self.srv.layer.delete_bucket(bucket)
        self.srv.bucket_meta.drop(bucket)
        if self.srv.federation is not None:
            self.srv.federation.unregister(bucket)
        return self._send(204)
    if cmd == "GET":
        self._allow(iampol.LIST_BUCKET, bucket)
        return self._list_objects(bucket, query)
    raise S3Error("MethodNotAllowed")

def _post_policy_upload(self, bucket, payload):
    """Browser POST upload (cmd/object-handlers.go
    PostPolicyBucketHandler): authenticate via the policy
    signature in the form, validate conditions, store the file
    field as the object."""
    from . import postpolicy
    try:
        fields, file_data, filename = postpolicy.parse_form(
            payload, self.headers.get("Content-Type", ""))
        key = fields.get("key", "")
        if not key:
            raise S3Error("InvalidArgument")
        key = key.replace("${filename}", filename)
        self.access_key = postpolicy.verify_signature(
            self.srv.iam.lookup_secret, fields, self.srv.region)
        postpolicy.check_policy(
            fields.get("policy", ""),
            {**fields, "key": key, "bucket": bucket},
            len(file_data))
    except sigv4.SigV4Error as e:
        raise S3Error(e.code if s3err.has(e.code)
                      else "AccessDenied") from e
    self._allow(iampol.PUT_OBJECT, f"{bucket}/{key}")
    if len(file_data) > MAX_OBJECT_SIZE:
        raise S3Error("EntityTooLarge")
    user_defined = {}
    if fields.get("content-type"):
        user_defined["content-type"] = fields["content-type"]
    for k, v in fields.items():
        if k.startswith("x-amz-meta-"):
            user_defined[k] = v
    if fields.get("tagging"):
        from ..bucket import tags as btags
        try:
            user_defined["x-amz-tagging"] = btags.to_header(
                btags.parse_xml(fields["tagging"].encode()))
        except btags.TagError as e:
            raise S3Error("InvalidTag") from e
    oi, hdrs = self._store_object(bucket, key, file_data,
                                  user_defined,
                                  "s3:ObjectCreated:Post")
    hdrs["Location"] = f"/{bucket}/{urllib.parse.quote(key)}"
    redirect = fields.get("success_action_redirect", "")
    if redirect:
        sep = "&" if "?" in redirect else "?"
        hdrs["Location"] = redirect + sep + urllib.parse.urlencode(
            {"bucket": bucket, "key": key, "etag": f'"{oi.etag}"'})
        return self._send(303, headers=hdrs)
    status = fields.get("success_action_status", "204")
    if status == "201":
        root = ET.Element("PostResponse")
        ET.SubElement(root, "Location").text = hdrs["Location"]
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "ETag").text = hdrs["ETag"]
        return self._send(201, _xml(root), headers=hdrs)
    return self._send(200 if status == "200" else 204,
                      headers=hdrs)

def _put_versioning(self, bucket, payload):
    self.srv.layer.get_bucket_info(bucket)
    try:
        root = ET.fromstring(payload)
        status = root.findtext(f"{{{S3_NS}}}Status") or \
            root.findtext("Status") or ""
    except ET.ParseError as e:
        raise S3Error("MalformedXML") from e
    if status != "Enabled" and \
            self.srv.bucket_meta.get_config(bucket,
                                       "object-lock") is not None:
        # object-lock buckets must stay versioned (AWS
        # InvalidBucketState)
        raise S3Error("InvalidBucketState")
    self.srv.bucket_meta.set_versioning(bucket, status == "Enabled")
    self._send(200)

def _get_versioning(self, bucket):
    self.srv.layer.get_bucket_info(bucket)
    root = ET.Element("VersioningConfiguration", xmlns=S3_NS)
    doc = self.srv.bucket_meta.get(bucket).get("versioning")
    if doc:
        ET.SubElement(root, "Status").text = doc["status"]
    self._send(200, _xml(root))

def _listen_notification(self, bucket, query):
    """Live event stream (cmd/listen-notification-handlers.go):
    newline-delimited JSON records, chunked; filters by prefix/
    suffix/event-name glob.  `timeout` bounds the stream so HTTP
    clients without explicit cancel (and tests) can use it."""
    import json as _json

    from ..bucket.notification import match_pattern
    self.srv.layer.get_bucket_info(bucket)
    q1 = {k: v[0] for k, v in query.items()}
    prefix = q1.get("prefix", "")
    suffix = q1.get("suffix", "")
    names = query.get("events", []) or ["*"]
    try:
        timeout = min(float(q1.get("timeout", 10) or 10), 300.0)
        max_events = int(q1.get("max-events", 1000) or 1000)
    except ValueError as e:
        raise S3Error("InvalidArgument") from e

    def want(item):
        if item["bucket"] != bucket:
            return False
        key = item["key"]
        if prefix and not key.startswith(prefix):
            return False
        if suffix and not key.endswith(suffix):
            return False
        return any(n == "*" or match_pattern(n, item["name"])
                   for n in names)

    self.send_response(200)
    self.send_header("Content-Type", "application/json")
    self.send_header("Transfer-Encoding", "chunked")
    self.end_headers()

    def write_chunk(data: bytes):
        self.wfile.write(f"{len(data):x}\r\n".encode())
        self.wfile.write(data + b"\r\n")
        self.wfile.flush()

    with self.srv.events.pubsub.subscribe(want) as sub:
        try:
            for item in sub.drain(max_events, timeout):
                line = _json.dumps(
                    {"Records": [item["record"]]}).encode() + b"\n"
                write_chunk(line)
        except (BrokenPipeError, ConnectionResetError):
            pass
        try:
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass

def _encoding_type(self, q1):
    """encoding-type handling shared by every listing API:
    returns (escape_fn, enabled).  Keys may contain characters
    XML 1.0 cannot carry; url encoding (the awscli/boto3
    default) percent-encodes them in responses."""
    enc = q1.get("encoding-type", "")
    if enc and enc != "url":
        raise S3Error("InvalidArgument")
    if enc:
        return (lambda s: urllib.parse.quote(s or "", safe="/"),
                True)
    return (lambda s: s), False

def _list_objects(self, bucket, query):
    from ..objectlayer import metacache as mcache
    q1 = {k: v[0] for k, v in query.items()}
    v2 = q1.get("list-type") == "2"
    prefix = q1.get("prefix", "")
    delimiter = q1.get("delimiter", "")
    max_keys = min(int(q1.get("max-keys", 1000) or 1000), 1000)
    if v2 and q1.get("continuation-token"):
        # opaque V2 tokens decode to the resume key; a malformed token
        # is the client's error (InvalidArgument), and one that
        # outlived its snapshot generation simply resumes from the key
        # over a fresh walk — never a 500 (metacache.decode_list_token)
        try:
            marker = mcache.decode_list_token(q1["continuation-token"])
        except ValueError as e:
            raise S3Error("InvalidArgument") from e
    else:
        marker = q1.get("marker", "") if not v2 else ""
        marker = marker or q1.get("start-after", "")
    esc, enc = self._encoding_type(q1)
    res = self.srv.layer.list_objects(bucket, prefix, marker, delimiter,
                                 max_keys)
    name = "ListBucketResult"
    root = ET.Element(name, xmlns=S3_NS)
    ET.SubElement(root, "Name").text = bucket
    ET.SubElement(root, "Prefix").text = esc(prefix)
    if delimiter:
        ET.SubElement(root, "Delimiter").text = esc(delimiter)
    if enc:
        ET.SubElement(root, "EncodingType").text = "url"
    ET.SubElement(root, "MaxKeys").text = str(max_keys)
    ET.SubElement(root, "IsTruncated").text = \
        "true" if res.is_truncated else "false"
    if v2:
        ET.SubElement(root, "KeyCount").text = \
            str(len(res.objects) + len(res.prefixes))
        if q1.get("continuation-token"):
            # tokens are OPAQUE to clients: AWS excludes them
            # from encoding-type, and clients echo them verbatim
            # — encoding here would corrupt pagination
            ET.SubElement(root, "ContinuationToken").text = \
                q1["continuation-token"]
        if q1.get("start-after"):
            ET.SubElement(root, "StartAfter").text = \
                esc(q1["start-after"])
        if res.is_truncated:
            ET.SubElement(root, "NextContinuationToken").text = \
                mcache.encode_list_token(res.next_marker)
    else:
        ET.SubElement(root, "Marker").text = esc(marker)
        if res.is_truncated:
            ET.SubElement(root, "NextMarker").text = \
                esc(res.next_marker)
    fetch_owner = (not v2) or q1.get("fetch-owner") == "true"
    for o in res.objects:
        c = ET.SubElement(root, "Contents")
        ET.SubElement(c, "Key").text = esc(o.name)
        ET.SubElement(c, "LastModified").text = _iso_date(o.mod_time)
        ET.SubElement(c, "ETag").text = f'"{o.etag}"'
        ET.SubElement(c, "Size").text = str(_actual_size(o))
        ET.SubElement(c, "StorageClass").text = \
            o.user_defined.get("x-amz-storage-class", "STANDARD")
        if fetch_owner:
            owner = ET.SubElement(c, "Owner")
            ET.SubElement(owner, "ID").text = "minio-tpu"
            ET.SubElement(owner, "DisplayName").text = "minio-tpu"
    for p in res.prefixes:
        cp = ET.SubElement(root, "CommonPrefixes")
        ET.SubElement(cp, "Prefix").text = esc(p)
    self._send(200, _xml(root))

def _list_object_versions(self, bucket, query):
    q1 = {k: v[0] for k, v in query.items()}
    prefix = q1.get("prefix", "")
    esc, enc = self._encoding_type(q1)
    versions = self.srv.layer.list_object_versions(bucket, prefix)
    root = ET.Element("ListVersionsResult", xmlns=S3_NS)
    ET.SubElement(root, "Name").text = bucket
    ET.SubElement(root, "Prefix").text = esc(prefix)
    if enc:
        ET.SubElement(root, "EncodingType").text = "url"
    ET.SubElement(root, "IsTruncated").text = "false"
    for o in versions:
        tag = "DeleteMarker" if o.delete_marker else "Version"
        v = ET.SubElement(root, tag)
        ET.SubElement(v, "Key").text = esc(o.name)
        ET.SubElement(v, "VersionId").text = o.version_id or "null"
        ET.SubElement(v, "IsLatest").text = \
            "true" if o.is_latest else "false"
        ET.SubElement(v, "LastModified").text = _iso_date(o.mod_time)
        if not o.delete_marker:
            ET.SubElement(v, "ETag").text = f'"{o.etag}"'
            ET.SubElement(v, "Size").text = str(_actual_size(o))
            ET.SubElement(v, "StorageClass").text = "STANDARD"
    self._send(200, _xml(root))

def _list_uploads(self, bucket, query):
    q1 = {k: v[0] for k, v in query.items()}
    esc, enc = self._encoding_type(q1)
    uploads = self.srv.layer.list_multipart_uploads(
        bucket, q1.get("prefix", ""))
    root = ET.Element("ListMultipartUploadsResult", xmlns=S3_NS)
    ET.SubElement(root, "Bucket").text = bucket
    if enc:
        ET.SubElement(root, "EncodingType").text = "url"
    ET.SubElement(root, "IsTruncated").text = "false"
    for u in uploads:
        ue = ET.SubElement(root, "Upload")
        ET.SubElement(ue, "Key").text = esc(u.object_name)
        ET.SubElement(ue, "UploadId").text = u.upload_id
    self._send(200, _xml(root))

def _delete_objects(self, bucket, payload):
    try:
        root = ET.fromstring(payload)
    except ET.ParseError as e:
        raise S3Error("MalformedXML") from e
    ns = f"{{{S3_NS}}}"
    quiet = (root.findtext(f"{ns}Quiet") or
             root.findtext("Quiet") or "") == "true"
    out = ET.Element("DeleteResult", xmlns=S3_NS)
    versioned = self.srv.bucket_meta.versioning_enabled(bucket)
    for obj in (root.findall(f"{ns}Object") +
                root.findall("Object")):
        key = obj.findtext(f"{ns}Key") or obj.findtext("Key")
        vid = obj.findtext(f"{ns}VersionId") or \
            obj.findtext("VersionId")
        try:
            self._allow(iampol.DELETE_OBJECT, f"{bucket}/{key}")
            self._check_retention(bucket, key, vid)
            tiered_ud = self._tiered_meta_of(bucket, key, vid,
                                             versioned)
            res = self.srv.layer.delete_object(
                bucket, key,
                ol.ObjectOptions(version_id=vid,
                                 versioned=versioned))
            if tiered_ud is not None:
                self.srv.transition.delete_tiered(tiered_ud)
            if not quiet:
                d = ET.SubElement(out, "Deleted")
                ET.SubElement(d, "Key").text = key
                if res.delete_marker:
                    ET.SubElement(d, "DeleteMarker").text = "true"
                    ET.SubElement(d,
                                  "DeleteMarkerVersionId").text = \
                        res.version_id
        except Exception as e:  # noqa: BLE001
            if isinstance(e, S3Error):
                api = e.api
            elif isinstance(e, ol.ObjectLayerError):
                api = s3err.from_object_error(e)
            else:
                api = s3err.get("InternalError")
            err = ET.SubElement(out, "Error")
            ET.SubElement(err, "Key").text = key
            ET.SubElement(err, "Code").text = api.code
            ET.SubElement(err, "Message").text = api.description
    self._send(200, _xml(out))

# -- object APIs ---------------------------------------------------


# handler methods _make_handler attaches to the request class
HANDLERS = [
    "_list_buckets", "_config_api", "_bucket_api", "_post_policy_upload",
    "_put_versioning", "_get_versioning", "_listen_notification",
    "_encoding_type", "_list_objects", "_list_object_versions",
    "_list_uploads", "_delete_objects",
]
