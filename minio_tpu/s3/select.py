"""SelectObjectContent glue: the S3 handler's entry into minio_tpu.s3select
(pkg/s3select.NewS3Select + Evaluate in the reference)."""

from __future__ import annotations

from ..s3select import SelectError, run_select  # noqa: F401 — re-export


def run(payload: bytes, data: bytes, content_type: str = "") -> bytes:
    """Execute the SelectObjectContentRequest in `payload` against object
    bytes `data`; returns the framed event-stream body."""
    return run_select(payload, data)
