"""S3 API error taxonomy + XML rendering (cmd/api-errors.go, ~300 codes in
the reference; here the subset the implemented APIs can produce, extended as
handlers land).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from dataclasses import dataclass

from ..objectlayer import interface as ol


@dataclass(frozen=True)
class APIError:
    code: str
    description: str
    http_status: int


_ERRORS = {
    "AccessDenied": APIError("AccessDenied", "Access Denied.", 403),
    "BadDigest": APIError(
        "BadDigest", "The Content-Md5 you specified did not match what we "
        "received.", 400),
    "BucketAlreadyExists": APIError(
        "BucketAlreadyExists", "The requested bucket name is not "
        "available. The bucket namespace is shared by all users of the "
        "system.", 409),
    "BucketAlreadyOwnedByYou": APIError(
        "BucketAlreadyOwnedByYou",
        "Your previous request to create the named bucket succeeded and you "
        "already own it.", 409),
    "BucketNotEmpty": APIError(
        "BucketNotEmpty", "The bucket you tried to delete is not empty.",
        409),
    "EntityTooLarge": APIError(
        "EntityTooLarge", "Your proposed upload exceeds the maximum allowed "
        "object size.", 400),
    "ExpiredToken": APIError(
        "ExpiredToken", "The provided token has expired.", 400),
    "IncompleteBody": APIError(
        "IncompleteBody", "You did not provide the number of bytes "
        "specified by the Content-Length HTTP header.", 400),
    "InternalError": APIError(
        "InternalError", "We encountered an internal error, please try "
        "again.", 500),
    "InvalidAccessKeyId": APIError(
        "InvalidAccessKeyId", "The Access Key Id you provided does not "
        "exist in our records.", 403),
    "InvalidArgument": APIError(
        "InvalidArgument", "Invalid Argument", 400),
    "InvalidBucketName": APIError(
        "InvalidBucketName", "The specified bucket is not valid.", 400),
    "InvalidDigest": APIError(
        "InvalidDigest", "The Content-Md5 you specified is not valid.", 400),
    "InvalidPart": APIError(
        "InvalidPart", "One or more of the specified parts could not be "
        "found.", 400),
    "InvalidPartOrder": APIError(
        "InvalidPartOrder", "The list of parts was not in ascending order.",
        400),
    "InvalidRange": APIError(
        "InvalidRange", "The requested range is not satisfiable", 416),
    "InvalidRequest": APIError("InvalidRequest", "Invalid Request", 400),
    "MalformedXML": APIError(
        "MalformedXML", "The XML you provided was not well-formed or did "
        "not validate against our published schema.", 400),
    "MethodNotAllowed": APIError(
        "MethodNotAllowed", "The specified method is not allowed against "
        "this resource.", 405),
    "MissingContentLength": APIError(
        "MissingContentLength", "You must provide the Content-Length HTTP "
        "header.", 411),
    "NoSuchBucket": APIError(
        "NoSuchBucket", "The specified bucket does not exist", 404),
    "NoSuchKey": APIError(
        "NoSuchKey", "The specified key does not exist.", 404),
    "NoSuchUpload": APIError(
        "NoSuchUpload", "The specified multipart upload does not exist. "
        "The upload ID may be invalid, or the upload may have been aborted "
        "or completed.", 404),
    "NoSuchVersion": APIError(
        "NoSuchVersion", "The specified version does not exist.", 404),
    "InvalidStorageClass": APIError(
        "InvalidStorageClass", "The storage class you specified is not "
        "valid", 400),
    "InvalidObjectState": APIError(
        "InvalidObjectState", "The operation is not valid for the "
        "object's storage class", 403),
    "NotImplemented": APIError(
        "NotImplemented", "A header you provided implies functionality "
        "that is not implemented", 501),
    "PreconditionFailed": APIError(
        "PreconditionFailed", "At least one of the pre-conditions you "
        "specified did not hold", 412),
    "RequestTimeTooSkewed": APIError(
        "RequestTimeTooSkewed", "The difference between the request time "
        "and the server's time is too large.", 403),
    "SignatureDoesNotMatch": APIError(
        "SignatureDoesNotMatch", "The request signature we calculated does "
        "not match the signature you provided. Check your key and signing "
        "method.", 403),
    "AuthorizationHeaderMalformed": APIError(
        "AuthorizationHeaderMalformed",
        "The authorization header is malformed.", 400),
    "AuthorizationQueryParametersError": APIError(
        "AuthorizationQueryParametersError",
        "Error parsing the X-Amz-Credential parameter.", 400),
    "SlowDown": APIError(
        "SlowDown", "Resource requested is unreadable, please reduce your "
        "request rate", 503),
    "RequestTimeout": APIError(
        "RequestTimeout", "Your socket connection to the server was not "
        "read from or written to within the timeout period.", 408),
    "XMinioServerNotInitialized": APIError(
        "XMinioServerNotInitialized", "Server not initialized yet, please "
        "try again.", 503),
    "NoSuchBucketPolicy": APIError(
        "NoSuchBucketPolicy", "The bucket policy does not exist", 404),
    "NoSuchLifecycleConfiguration": APIError(
        "NoSuchLifecycleConfiguration",
        "The lifecycle configuration does not exist", 404),
    "ReplicationConfigurationNotFoundError": APIError(
        "ReplicationConfigurationNotFoundError",
        "The replication configuration was not found", 404),
    "ServerSideEncryptionConfigurationNotFoundError": APIError(
        "ServerSideEncryptionConfigurationNotFoundError",
        "The server side encryption configuration was not found", 404),
    "ObjectLockConfigurationNotFoundError": APIError(
        "ObjectLockConfigurationNotFoundError",
        "Object Lock configuration does not exist for this bucket", 404),
    "InvalidBucketObjectLockConfiguration": APIError(
        "InvalidBucketObjectLockConfiguration",
        "Bucket is missing ObjectLockConfiguration", 400),
    "NoSuchObjectLockConfiguration": APIError(
        "NoSuchObjectLockConfiguration",
        "The specified object does not have an ObjectLock configuration",
        404),
    "ObjectLocked": APIError(
        "ObjectLocked", "Object is WORM protected and cannot be "
        "overwritten or deleted", 400),
    "NoSuchTagSet": APIError(
        "NoSuchTagSet", "The TagSet does not exist", 404),
    "InvalidTag": APIError(
        "InvalidTag", "The tag provided was not a valid tag. A provided "
        "tag key or value was invalid.", 400),
    "MalformedPolicy": APIError(
        "MalformedPolicy", "Policy has invalid resource.", 400),
    "NoSuchWebsiteConfiguration": APIError(
        "NoSuchWebsiteConfiguration",
        "The specified bucket does not have a website configuration", 404),
    "NoSuchCORSConfiguration": APIError(
        "NoSuchCORSConfiguration",
        "The CORS configuration does not exist", 404),
    "BadRequest": APIError("BadRequest", "400 BadRequest", 400),
    "InvalidBucketState": APIError(
        "InvalidBucketState", "The request is not valid with the current "
        "state of the bucket.", 409),
    "AdminBucketQuotaExceeded": APIError(
        "XMinioAdminBucketQuotaExceeded",
        "Bucket quota may be exceeded with this request.", 403),
    "ReplicationDestinationNotFoundError": APIError(
        "ReplicationDestinationNotFoundError",
        "The replication destination bucket does not exist", 404),
    # SSE (cmd/api-errors.go crypto section)
    "InvalidEncryptionAlgorithmError": APIError(
        "InvalidEncryptionAlgorithmError",
        "The Encryption request you specified is not valid. Supported "
        "value: AES256.", 400),
    "SSECustomerKeyMD5Mismatch": APIError(
        "InvalidArgument",
        "The calculated MD5 hash of the key did not match the hash that "
        "was provided.", 400),
    "SSEEncryptedObject": APIError(
        "InvalidRequest", "The object was stored using a form of Server "
        "Side Encryption. The correct parameters must be provided to "
        "retrieve the object.", 400),
    "InsecureSSECustomerRequest": APIError(
        "InvalidRequest", "Requests specifying Server Side Encryption "
        "with Customer provided keys must be made over a secure "
        "connection.", 400),
    "KMSNotConfigured": APIError(
        "KMSNotConfigured", "Server side encryption specified but KMS is "
        "not configured", 400),
    "InvalidCopySource": APIError(
        "InvalidArgument", "Copy Source must mention the source bucket "
        "and key: sourcebucket/sourcekey.", 400),
    "InvalidCopyDest": APIError(
        "InvalidRequest", "This copy request is illegal because it is "
        "trying to copy an object to itself without changing the "
        "object's metadata, storage class, website redirect location or "
        "encryption attributes.", 400),
    # S3 Select (cmd/api-errors.go select section)
    "ParseSelectFailure": APIError(
        "ParseSelectFailure", "The SQL expression contains an invalid "
        "token or is otherwise not parseable.", 400),
    "EvaluatorInvalidArguments": APIError(
        "EvaluatorInvalidArguments", "Incorrect number of arguments in "
        "the function call or invalid evaluation.", 400),
    "InvalidExpressionType": APIError(
        "InvalidExpressionType", "The ExpressionType is invalid. Only "
        "SQL expressions are supported.", 400),
    "InvalidDataSource": APIError(
        "InvalidDataSource", "Invalid data source type. Only CSV and "
        "JSON are supported.", 400),
    "InvalidCompressionFormat": APIError(
        "InvalidCompressionFormat", "The file is not in a supported "
        "compression format. Only GZIP is supported.", 400),
    "InvalidRequestParameter": APIError(
        "InvalidRequestParameter", "The value of a parameter in "
        "SelectRequest element is invalid.", 400),
    "CSVParsingError": APIError(
        "CSVParsingError", "Encountered an error parsing the CSV file. "
        "Check the file and try again.", 400),
    "JSONParsingError": APIError(
        "JSONParsingError", "Encountered an error parsing the JSON file. "
        "Check the file and try again.", 400),
    "MalformedPOSTRequest": APIError(
        "MalformedPOSTRequest", "The body of your POST request is not "
        "well-formed multipart/form-data.", 400),
    "EntityTooSmall": APIError(
        "EntityTooSmall", "Your proposed upload is smaller than the "
        "minimum allowed object size.", 400),
}


def get(code: str) -> APIError:
    return _ERRORS.get(code, _ERRORS["InternalError"])


def has(code: str) -> bool:
    return code in _ERRORS


def from_object_error(e: Exception) -> APIError:
    """Map object layer errors to S3 codes
    (toAPIErrorCode, cmd/api-errors.go)."""
    mapping = {
        ol.BucketNotFound: "NoSuchBucket",
        ol.BucketExists: "BucketAlreadyOwnedByYou",
        ol.BucketNotEmpty: "BucketNotEmpty",
        ol.BucketNameInvalid: "InvalidBucketName",
        ol.ObjectNotFound: "NoSuchKey",
        ol.VersionNotFound: "NoSuchVersion",
        ol.MethodNotAllowed: "MethodNotAllowed",
        ol.ObjectNameInvalid: "InvalidArgument",
        ol.InvalidRange: "InvalidRange",
        ol.ReadQuorumError: "SlowDown",
        ol.WriteQuorumError: "SlowDown",
        ol.InvalidUploadID: "NoSuchUpload",
        ol.InvalidPart: "InvalidPart",
        ol.InvalidPartOrder: "InvalidPartOrder",
        ol.PreconditionFailed: "PreconditionFailed",
    }
    return get(mapping.get(type(e), "InternalError"))


def to_xml(err: APIError, resource: str = "", request_id: str = "") -> bytes:
    root = ET.Element("Error")
    ET.SubElement(root, "Code").text = err.code
    ET.SubElement(root, "Message").text = err.description
    ET.SubElement(root, "Resource").text = resource
    ET.SubElement(root, "RequestId").text = request_id
    return (b'<?xml version="1.0" encoding="UTF-8"?>' +
            ET.tostring(root))
