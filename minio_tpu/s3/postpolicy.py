"""Browser POST uploads — policy form parsing and verification
(cmd/postpolicyform.go, cmd/object-handlers.go PostPolicyBucketHandler,
policy signature checks in cmd/signature-v4-utils.go /
cmd/signature-v2.go doesPolicySignatureV2Match).

A POST upload is a multipart/form-data body whose fields include a
base64 policy document, its signature (V4 or V2), and the object bytes
in the ``file`` field.  The policy document carries an expiration plus
conditions every form field must satisfy.
"""

from __future__ import annotations

import base64
import datetime
import email.parser
import hashlib
import hmac
import json
from typing import Tuple

from . import sigv4
from .sigv4 import SigV4Error as SigError


def parse_form(body: bytes, content_type: str
               ) -> Tuple[dict[str, str], bytes, str]:
    """Parse multipart/form-data; returns (fields, file_bytes, filename).
    Field names are lower-cased (the reference canonicalizes likewise)."""
    msg = email.parser.BytesParser().parsebytes(
        b"Content-Type: " + content_type.encode() + b"\r\n\r\n" + body)
    if not msg.is_multipart():
        raise SigError("MalformedPOSTRequest", "not multipart/form-data")
    fields: dict[str, str] = {}
    file_data = b""
    filename = ""
    for part in msg.get_payload():
        name = part.get_param("name", header="content-disposition")
        if not name:
            continue
        payload = part.get_payload(decode=True) or b""
        if name == "file":
            file_data = payload
            filename = part.get_param(
                "filename", header="content-disposition") or ""
        else:
            fields[name.lower()] = payload.decode("utf-8", "replace")
    return fields, file_data, filename


def _parse_expiration(policy: dict) -> float:
    exp = policy.get("expiration")
    if not exp:
        raise SigError("AccessDenied", "policy missing expiration")
    for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
        try:
            return datetime.datetime.strptime(exp, fmt).replace(
                tzinfo=datetime.timezone.utc).timestamp()
        except ValueError:
            continue
    raise SigError("AccessDenied", "malformed policy expiration")


def check_policy(policy_b64: str, fields: dict[str, str],
                 file_size: int, now: float | None = None) -> None:
    """checkPostPolicy (cmd/postpolicyform.go:178): every condition in the
    policy must hold against the submitted form fields."""
    import time as _time
    try:
        policy = json.loads(base64.b64decode(policy_b64))
    except (ValueError, json.JSONDecodeError) as e:
        raise SigError("MalformedPOSTRequest", "bad policy document") from e
    if not isinstance(policy, dict):
        raise SigError("MalformedPOSTRequest", "policy must be an object")
    if (now if now is not None else _time.time()) > \
            _parse_expiration(policy):
        raise SigError("AccessDenied", "policy document has expired")
    conditions = policy.get("conditions", [])
    if not isinstance(conditions, list):
        raise SigError("MalformedPOSTRequest", "conditions must be a list")
    for cond in conditions:
        if isinstance(cond, dict):
            for k, v in cond.items():
                got = fields.get(k.lower(), "")
                if got != str(v):
                    raise SigError(
                        "AccessDenied",
                        f"policy condition failed: eq ${k}")
        elif isinstance(cond, list) and len(cond) == 3:
            op, target, value = cond
            op = str(op).lower()
            if op == "content-length-range":
                try:
                    lo, hi = int(target), int(value)
                except (TypeError, ValueError) as e:
                    raise SigError("MalformedPOSTRequest",
                                   "bad content-length-range bounds") \
                        from e
                if not (lo <= file_size <= hi):
                    raise SigError(
                        "EntityTooLarge" if file_size > hi
                        else "EntityTooSmall",
                        "content-length-range violated")
                continue
            key = str(target).lstrip("$").lower()
            got = fields.get(key, "")
            if op == "eq":
                ok = got == str(value)
            elif op == "starts-with":
                ok = got.startswith(str(value))
            else:
                raise SigError("AccessDenied",
                               f"unknown policy operator {op}")
            if not ok:
                raise SigError("AccessDenied",
                               f"policy condition failed: {op} ${key}")
        else:
            raise SigError("MalformedPOSTRequest", "bad policy condition")


def verify_signature(lookup_secret, fields: dict[str, str],
                     region: str) -> str:
    """Policy signature check; returns the authenticated access key.
    V4: signature over the base64 policy with the SigV4 signing key.
    V2: base64 HMAC-SHA1 of the policy (doesPolicySignatureV2Match)."""
    policy = fields.get("policy", "")
    if not policy:
        raise SigError("AccessDenied", "missing policy field")
    if fields.get("x-amz-algorithm", "") == sigv4.ALGORITHM:
        cred = fields.get("x-amz-credential", "")
        amz_date = fields.get("x-amz-date", "")
        got = fields.get("x-amz-signature", "")
        parts = cred.split("/")
        if len(parts) != 5:
            raise SigError("AccessDenied", "malformed credential")
        access_key, date, cred_region, service, term = parts
        if service != "s3" or term != "aws4_request" or \
                cred_region != region:
            raise SigError("AccessDenied", "bad credential scope")
        if not amz_date.startswith(date):
            raise SigError("AccessDenied", "credential date mismatch")
        secret = lookup_secret(access_key)
        if secret is None:
            raise SigError("InvalidAccessKeyId", "no such key")
        key = sigv4.signing_key(secret, date, region, "s3")
        want = hmac.new(key, policy.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, got):
            raise SigError("SignatureDoesNotMatch",
                           "policy signature mismatch")
        return access_key
    if "awsaccesskeyid" in fields:
        access_key = fields["awsaccesskeyid"]
        got = fields.get("signature", "")
        secret = lookup_secret(access_key)
        if secret is None:
            raise SigError("InvalidAccessKeyId", "no such key")
        want = base64.b64encode(hmac.new(
            secret.encode(), policy.encode(), hashlib.sha1).digest()
        ).decode()
        if not hmac.compare_digest(want, got):
            raise SigError("SignatureDoesNotMatch",
                           "policy signature mismatch")
        return access_key
    raise SigError("AccessDenied", "no policy signature present")


def sign_policy_v4(access_key: str, secret_key: str, policy_doc: dict,
                   region: str, now: datetime.datetime | None = None
                   ) -> dict[str, str]:
    """Client-side helper: produce the form fields for a V4 POST upload
    (the shape browsers get from presignedPostPolicy SDK calls)."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    date = now.strftime("%Y%m%d")
    amz_date = now.strftime(sigv4.ISO8601)
    cred = f"{access_key}/{date}/{region}/s3/aws4_request"
    doc = dict(policy_doc)
    doc.setdefault("conditions", [])
    doc["conditions"] = list(doc["conditions"]) + [
        {"x-amz-algorithm": sigv4.ALGORITHM},
        {"x-amz-credential": cred},
        {"x-amz-date": amz_date},
    ]
    policy_b64 = base64.b64encode(
        json.dumps(doc).encode()).decode()
    key = sigv4.signing_key(secret_key, date, region, "s3")
    sig = hmac.new(key, policy_b64.encode(), hashlib.sha256).hexdigest()
    return {
        "policy": policy_b64,
        "x-amz-algorithm": sigv4.ALGORITHM,
        "x-amz-credential": cred,
        "x-amz-date": amz_date,
        "x-amz-signature": sig,
    }
