"""S3 object-level handlers (cmd/object-handlers.go, cmd/object-multipart-handlers.go).

Extracted from s3/server.py (round-3 split: the 2800-line monolith
became core plumbing + per-family handler modules with NO behavior
change).  Functions here are attached to the request-handler class by
_make_handler (server.py); ``self`` is the handler instance and
``self.srv`` the owning S3Server.
"""

from __future__ import annotations

import datetime
import email.utils
import hashlib
import urllib.parse
import xml.etree.ElementTree as ET

from ..iam import policy as iampol
from ..objectlayer import interface as ol
from . import errors as s3err
from . import sigv4
from .server import (MAX_OBJECT_SIZE, MAX_PUT_SIZE, S3_NS,
                     STREAM_PUT_THRESHOLD, S3Error, _BUCKET_RE,
                     _BodyReader, _MD5Reader, _canned_acl_xml,
                     _http_date, _iso_date, _layer_set_drive_count,
                     _parse_range, _try, _xml)

def _object_api(self, bucket, key, query, payload):
    cmd = self.command
    resource = f"{bucket}/{key}"
    if "tagging" in query:
        return self._object_tagging(bucket, key, query, payload)
    if "retention" in query:
        return self._object_retention(bucket, key, query, payload)
    if "legal-hold" in query:
        return self._object_legal_hold(bucket, key, query, payload)
    if "acl" in query:
        if cmd == "GET":
            self._allow(iampol.GET_OBJECT_ACL, resource)
            self.srv.layer.get_object_info(bucket, key)
            return self._send(200, _canned_acl_xml())
        if cmd == "PUT":
            self._allow(iampol.PUT_OBJECT_ACL, resource)
            if self.headers.get("x-amz-acl", "private") != "private":
                raise S3Error("NotImplemented")
            return self._send(200)
        raise S3Error("MethodNotAllowed")
    if cmd == "POST" and "select" in query and \
            query.get("select-type") == ["2"]:
        self._allow(iampol.GET_OBJECT, resource)
        return self._select_object(bucket, key, payload)
    if cmd == "POST" and "uploads" in query:
        self._allow(iampol.PUT_OBJECT, resource)
        return self._create_multipart(bucket, key)
    if cmd == "POST" and "uploadId" in query:
        self._allow(iampol.PUT_OBJECT, resource)
        return self._complete_multipart(bucket, key, query, payload)
    if cmd == "PUT" and "uploadId" in query and \
            "x-amz-copy-source" in self.headers:
        self._allow(iampol.PUT_OBJECT, resource)
        return self._upload_part_copy(bucket, key, query)
    if cmd == "PUT" and "uploadId" in query:
        self._allow(iampol.PUT_OBJECT, resource)
        return self._upload_part(bucket, key, query, payload)
    if cmd == "PUT" and "x-amz-copy-source" in self.headers:
        self._allow(iampol.PUT_OBJECT, resource)
        return self._copy_object(bucket, key, query)
    if cmd == "DELETE" and "uploadId" in query:
        self._allow(iampol.ABORT_MULTIPART, resource)
        self.srv.layer.abort_multipart_upload(bucket, key,
                                         query["uploadId"][0])
        return self._send(204)
    if cmd == "GET" and "uploadId" in query:
        self._allow(iampol.LIST_PARTS, resource)
        return self._list_parts(bucket, key, query)
    if cmd == "POST" and "restore" in query:
        self._allow("s3:RestoreObject", resource)
        return self._restore_object(bucket, key, query, payload)
    if cmd == "PUT":
        self._allow(iampol.PUT_OBJECT, resource)
        return self._put_object(bucket, key, query, payload)
    if cmd in ("GET", "HEAD"):
        self._allow(
            iampol.GET_OBJECT_VERSION if query.get("versionId")
            else iampol.GET_OBJECT, resource)
        return self._get_object(bucket, key, query,
                                head=(cmd == "HEAD"))
    if cmd == "DELETE":
        self._allow(
            iampol.DELETE_OBJECT_VERSION if query.get("versionId")
            else iampol.DELETE_OBJECT, resource)
        return self._delete_object(bucket, key, query)
    raise S3Error("MethodNotAllowed")

# -- object subresources (tagging/retention/legal-hold) ------------

TAG_KEY = "x-amz-tagging"  # metadata key holding url-encoded tags

def _vid(self, query) -> str | None:
    vid = query.get("versionId", [None])[0]
    return "" if vid == "null" else vid

def _object_tagging(self, bucket, key, query, payload):
    from ..bucket import tags as btags
    resource = f"{bucket}/{key}"
    vid = self._vid(query)
    if self.command == "PUT":
        self._allow(iampol.PUT_OBJECT_TAGGING, resource)
        t = _try(lambda: btags.parse_xml(payload))
        oi = self.srv.layer.put_object_metadata(
            bucket, key, vid, {self.TAG_KEY: btags.to_header(t)})
        self.srv.notify("s3:ObjectCreated:PutTagging", bucket, oi)
        return self._send(200)
    if self.command == "GET":
        self._allow(iampol.GET_OBJECT_TAGGING, resource)
        oi = self.srv.layer.get_object_info(
            bucket, key, ol.ObjectOptions(version_id=vid))
        t = btags.parse_header(
            oi.user_defined.get(self.TAG_KEY, ""))
        return self._send(200, btags.to_xml(t))
    if self.command == "DELETE":
        self._allow(iampol.DELETE_OBJECT_TAGGING, resource)
        oi = self.srv.layer.put_object_metadata(
            bucket, key, vid, {}, removes=(self.TAG_KEY,))
        self.srv.notify("s3:ObjectCreated:DeleteTagging", bucket, oi)
        return self._send(204)
    raise S3Error("MethodNotAllowed")

def _object_retention(self, bucket, key, query, payload):
    from ..bucket import objectlock as olock
    resource = f"{bucket}/{key}"
    vid = self._vid(query)
    if self.command == "PUT":
        self._allow(iampol.PUT_OBJECT_RETENTION, resource)
        if self.srv.bucket_meta.get_config(bucket, "object-lock") is None:
            raise S3Error("InvalidRequest")
        ret = _try(lambda: olock.Retention.parse(payload))
        # tightening is always allowed; loosening COMPLIANCE is not
        oi = self.srv.layer.get_object_info(
            bucket, key, ol.ObjectOptions(version_id=vid))
        cur = olock.Retention.from_metadata(oi.user_defined)
        if cur.active() and cur.mode == olock.COMPLIANCE and (
                ret.retain_until < cur.retain_until or
                ret.mode != olock.COMPLIANCE):
            raise S3Error("ObjectLocked")
        if cur.active() and cur.mode == olock.GOVERNANCE and \
                not self._governance_bypass(resource):
            if ret.retain_until < cur.retain_until or \
                    ret.mode != cur.mode:
                raise S3Error("ObjectLocked")
        oi = self.srv.layer.put_object_metadata(bucket, key, vid, {
            olock.AMZ_OBJECT_LOCK_MODE: ret.mode,
            olock.AMZ_OBJECT_LOCK_RETAIN_UNTIL:
                ret.retain_until.astimezone(
                    datetime.timezone.utc).strftime(
                        "%Y-%m-%dT%H:%M:%SZ"),
        })
        self.srv.notify("s3:ObjectCreated:PutRetention", bucket, oi)
        return self._send(200)
    if self.command == "GET":
        self._allow(iampol.GET_OBJECT_RETENTION, resource)
        oi = self.srv.layer.get_object_info(
            bucket, key, ol.ObjectOptions(version_id=vid))
        ret = olock.Retention.from_metadata(oi.user_defined)
        if not ret.mode:
            raise S3Error("NoSuchObjectLockConfiguration")
        return self._send(200, ret.to_xml())
    raise S3Error("MethodNotAllowed")

def _object_legal_hold(self, bucket, key, query, payload):
    from ..bucket import objectlock as olock
    resource = f"{bucket}/{key}"
    vid = self._vid(query)
    if self.command == "PUT":
        self._allow(iampol.PUT_OBJECT_LEGAL_HOLD, resource)
        if self.srv.bucket_meta.get_config(bucket, "object-lock") is None:
            raise S3Error("InvalidRequest")
        status = _try(lambda: olock.legal_hold_from_xml(payload))
        oi = self.srv.layer.put_object_metadata(
            bucket, key, vid,
            {olock.AMZ_OBJECT_LOCK_LEGAL_HOLD: status})
        self.srv.notify("s3:ObjectCreated:PutLegalHold", bucket, oi)
        return self._send(200)
    if self.command == "GET":
        self._allow(iampol.GET_OBJECT_LEGAL_HOLD, resource)
        oi = self.srv.layer.get_object_info(
            bucket, key, ol.ObjectOptions(version_id=vid))
        status = oi.user_defined.get(
            olock.AMZ_OBJECT_LOCK_LEGAL_HOLD, "OFF")
        return self._send(200, olock.legal_hold_to_xml(status))
    raise S3Error("MethodNotAllowed")

def _governance_bypass(self, resource: str) -> bool:
    if self.headers.get("x-amz-bypass-governance-retention",
                        "").lower() != "true":
        return False
    try:
        self._allow(iampol.BYPASS_GOVERNANCE, resource)
        return True
    except S3Error:
        return False

# frames accumulated past this switch the Select response from one
# buffered Content-Length body (small results — the wire shape every
# S3 SDK handled before streaming existed) to chunked transfer
# encoding written as the scan advances
SELECT_FLUSH_BYTES = 2 << 20
# working-set estimate one Select scanner charges to the memory
# governor: a few decode blocks + the pre-flush frame accumulation
SELECT_CHARGE_BLOCKS = 6

def _select_object(self, bucket, key, payload):
    from ..admin.metrics import GLOBAL as mtr
    from ..s3select import (SelectError, SelectRequest, message,
                            run_select_stream)
    from ..utils import close_quietly
    from ..utils.memgov import GOVERNOR
    block = self.srv.select_block_bytes
    # request shape first (malformed XML is the client's 400, never a
    # shed), and the object's identity — both feed the charge estimate
    try:
        req = SelectRequest.parse(payload)
    except SelectError as e:
        raise S3Error(e.code) from e
    oi = self.srv.layer.get_object_info(bucket, key)
    est = SELECT_CHARGE_BLOCKS * block + SELECT_FLUSH_BYTES
    if req.input_format == "PARQUET" or (
            req.input_format == "JSON" and
            req.input_opts.get("type", "LINES") != "LINES"):
        # whole-value inputs MATERIALIZE the decoded object (the
        # documented scanner fallback) — the charge must say so, or
        # the governor admits the very OOM it exists to shed
        est += 2 * self._plain_size_estimate(oi)
    # admission BEFORE any data is pulled: under memory pressure the
    # scan is shed with 503 + Retry-After, not started (memgov.py)
    charge = GOVERNOR.charge(est, "select")
    chunks = None
    try:
        mtr.inc("mt_select_requests_total")

        def on_stats(scanned, processed, returned):
            mtr.inc("mt_select_scanned_bytes_total", value=scanned)
            mtr.inc("mt_select_processed_bytes_total", value=processed)
            mtr.inc("mt_select_returned_bytes_total", value=returned)

        _, chunks = self._fetch_plain_chunks(bucket, key, block, oi=oi)
        try:
            frames = run_select_stream(payload, chunks,
                                       block_bytes=block,
                                       on_stats=on_stats)
        except SelectError as e:
            raise S3Error(e.code) from e
        # hybrid send: accumulate frames up to the flush threshold —
        # small results (and every pre-streaming test vector) keep the
        # exact buffered wire shape; past it, switch to chunked and
        # write frames as the scanner emits them (O(block) memory for
        # multi-GiB scans).  An error BEFORE the response commits is a
        # clean 400; after, it becomes an in-stream error frame (the
        # reference's mid-stream error message semantics).
        it = iter(frames)
        head = bytearray()
        done = False
        try:
            while len(head) < SELECT_FLUSH_BYTES:
                try:
                    head += next(it)
                except StopIteration:
                    done = True
                    break
        except SelectError as e:
            raise S3Error(e.code) from e
        if done:
            return self._send(200, bytes(head),
                              content_type="application/octet-stream")

        def tail():
            try:
                yield from it
            except SelectError as e:
                yield message.error_message(e.code, str(e))

        self._send_chunked(200, tail(), "application/octet-stream",
                           head=bytes(head))
    finally:
        charge.release()
        close_quietly(chunks)

def _plain_size_estimate(self, oi) -> int:
    """Decoded-size estimate for governor charges: the recorded
    pre-compression size when compressed, the DARE-plaintext size when
    encrypted-only, else the stored size."""
    from ..crypto import sse as csse
    raw = oi.user_defined.get(csse.META_ACTUAL_SIZE)
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    if csse.is_encrypted(oi.user_defined):
        try:
            return csse.decrypted_size(oi.user_defined, oi.size,
                                       oi.parts)
        except Exception:  # noqa: BLE001 — corrupt meta: stored size
            pass
    return oi.size


class _SeqCipherReader:
    """read(offset, n) over ONE streaming layer reader for callers
    whose offsets advance monotonically (the block-by-block SSE-C
    decrypt): the namespace lock and quorum metadata are taken once
    for the whole scan instead of once per block.  A backward request
    (shouldn't happen — decrypt ranges advance) falls back to a
    ranged layer read."""

    def __init__(self, layer, bucket, key, chunks):
        self._layer = layer
        self._bucket = bucket
        self._key = key
        self._chunks = chunks
        self._buf = bytearray()
        self._start = 0                  # object offset of buf[0]

    def read(self, offset: int, n: int) -> bytes:
        if offset < self._start:
            return self._layer.get_object(self._bucket, self._key,
                                          offset, n)[1]
        drop = offset - self._start
        while len(self._buf) < drop + n:
            try:
                piece = next(self._chunks)
            except StopIteration:
                break
            self._buf += piece
        if drop:
            del self._buf[:drop]
            self._start = offset
        out = bytes(self._buf[:n])
        del self._buf[:n]
        self._start += len(out)
        return out

    def close(self) -> None:
        from ..utils import close_quietly
        close_quietly(self._chunks)


def _fetch_plain_chunks(self, bucket, key, block: int, oi=None):
    """Decoded object bytes as (info, chunk iterator): SSE-C decrypt
    runs range-by-range (only covering DARE packages per block, fed
    from one sequential ciphertext stream) and transparent
    decompression streams frame-at-a-time, so a consumer holds
    O(block) however large the object — the chunked successor of the
    old whole-buffer _fetch_plain."""
    from .. import compress as mtc
    from ..crypto import sse as csse
    if oi is None:
        oi = self.srv.layer.get_object_info(bucket, key)
    if csse.is_encrypted(oi.user_defined):
        enc = csse.ObjectEncryption.open(
            oi.user_defined, bucket, key, self.headers, self.srv.kms)
        plain_size = csse.decrypted_size(oi.user_defined, oi.size,
                                         oi.parts)

        def dec_chunks():
            _, cipher = self.srv.layer.get_object_reader(bucket, key,
                                                         0, -1)
            seq = _SeqCipherReader(self.srv.layer, bucket, key,
                                   iter(cipher))
            try:
                off = 0
                while off < plain_size:
                    n = min(block, plain_size - off)
                    yield csse.decrypt_object_range(
                        enc, oi.user_defined, oi.size, seq.read, off,
                        n, oi.parts)
                    off += n
            finally:
                seq.close()
        chunks = dec_chunks()
    else:
        _, chunks = self.srv.layer.get_object_reader(bucket, key, 0, -1)
    if mtc.META_COMPRESSION in oi.user_defined:
        chunks = mtc.decompress_chunks(chunks)
    return oi, chunks

def _check_quota(self, bucket: str, nbytes: int) -> None:
    """Hard-quota admission (cmd/bucket-quota.go
    enforceBucketQuotaHard): rejects BEFORE any drive fan-out,
    charging the crawler snapshot + the in-flight byte delta
    (background/crawler.py UsageCache).  The quota config is read
    first so quota-free buckets pay nothing; ``quota.enable=off``
    is the operator kill switch."""
    if self.srv.usage is None:
        return
    from ..bucket.quota import Quota
    raw = self.srv.bucket_meta.get_config(bucket, "quota")
    if not raw:
        return
    if self.srv.config.get("quota", "enable") != "on":
        return
    if not Quota.parse(raw.encode()).allows(
            self.srv.usage.bucket_size(bucket), nbytes):
        raise S3Error("AdminBucketQuotaExceeded")

def _charge_quota_usage(self, bucket: str, nbytes: int) -> None:
    """A committed write moves the in-flight usage delta so the NEXT
    quota check sees these bytes (cleared when a crawler snapshot
    lands — the scan accounts them from then on)."""
    if self.srv.usage is not None and nbytes > 0:
        self.srv.usage.add_pending(bucket, nbytes)

# -- SSE helpers (cmd/encryption-v1.go) ----------------------------

def _bucket_sse_algo(self, bucket: str) -> str:
    """Bucket default-encryption algorithm, '' when unset."""
    from ..bucket.encryption import SSEConfig
    raw = self.srv.bucket_meta.get_config(bucket, "encryption")
    if not raw:
        return ""
    try:
        return SSEConfig.parse(raw.encode()).algorithm
    except ValueError:
        return ""

def _sse_for_put(self, bucket: str, key: str,
                 user_defined: dict) -> "object | None":
    """EncryptRequest analog: decide whether this PUT is SSE and
    mint the sealed object key into user_defined."""
    from ..crypto import sse as csse
    kind = csse.requested_sse(self.headers,
                              self._bucket_sse_algo(bucket))
    if not kind:
        return None
    enc = csse.ObjectEncryption.new(kind, bucket, key,
                                    self.headers, self.srv.kms)
    user_defined.update(enc.meta)
    return enc

def _compress_for_put(self, key: str, user_defined: dict,
                      payload: bytes) -> bytes:
    """Transparent compression (newS2CompressReader analog):
    applied BEFORE encryption, recorded via internal metadata with
    the original size for listings/HEAD."""
    from .. import compress as mtc
    from ..crypto import sse as csse
    if self.srv.config.get("compression", "enable") != "on":
        return payload
    exts = [e for e in self.srv.config.get(
        "compression", "extensions").split(",") if e]
    types = [t for t in self.srv.config.get(
        "compression", "mime_types").split(",") if t]
    ct = user_defined.get("content-type", "")
    if not mtc.is_compressible(key, ct, len(payload), exts, types):
        return payload
    user_defined[mtc.META_COMPRESSION] = mtc.COMPRESSION_ALGO
    user_defined[csse.META_ACTUAL_SIZE] = str(len(payload))
    return mtc.compress_stream(payload)

def _tagging_header_meta(self) -> dict[str, str]:
    """Validated x-amz-tagging header as metadata entries."""
    tag_hdr = self.headers.get("x-amz-tagging")
    if not tag_hdr:
        return {}
    from ..bucket import tags as btags
    _try(lambda: btags.parse_header(tag_hdr))
    return {self.TAG_KEY: tag_hdr}

def _create_multipart(self, bucket, key):
    user_defined = {}
    ct = self.headers.get("Content-Type")
    if ct:
        user_defined["content-type"] = ct
    for h, v in self.headers.items():
        if h.lower().startswith("x-amz-meta-"):
            user_defined[h.lower()] = v
    # same admission rules as PutObject: tagging header + object
    # lock defaults (a multipart upload must not dodge WORM)
    user_defined.update(self._tagging_header_meta())
    user_defined.update(self._lock_headers(bucket, key))
    from ..crypto import sse as csse
    self._sse_for_put(bucket, key, user_defined)
    versioned = self.srv.bucket_meta.versioning_enabled(bucket)
    uid = self.srv.layer.new_multipart_upload(
        bucket, key, ol.PutObjectOptions(
            user_defined=user_defined, versioned=versioned,
            parity=self._storage_class_parity(user_defined)))
    root = ET.Element("InitiateMultipartUploadResult", xmlns=S3_NS)
    ET.SubElement(root, "Bucket").text = bucket
    ET.SubElement(root, "Key").text = key
    ET.SubElement(root, "UploadId").text = uid
    self._send(200, _xml(root),
               headers=csse.response_headers(user_defined))

def _upload_part(self, bucket, key, query, payload):
    uid = query["uploadId"][0]
    try:
        part_num = int(query["partNumber"][0])
    except (KeyError, ValueError) as e:
        raise S3Error("InvalidArgument") from e
    self._check_quota(bucket, len(payload))
    payload, sse_hdrs = self._encrypt_part(bucket, key, uid,
                                           payload)
    pi = self.srv.layer.put_object_part(bucket, key, uid, part_num,
                                   payload)
    self._charge_quota_usage(bucket, pi.size)
    self._send(200, headers={"ETag": f'"{pi.etag}"', **sse_hdrs})

def _encrypt_part(self, bucket, key, uid,
                  payload) -> tuple[bytes, dict]:
    """Encrypt one part under the upload's sealed OEK as its own
    DARE stream (SSE-C requires the key headers on every part)."""
    from ..crypto import sse as csse
    mp = self.srv.layer.get_multipart_info(bucket, key, uid)
    if not csse.is_encrypted(mp.user_defined):
        return payload, {}
    enc = csse.ObjectEncryption.open(mp.user_defined, bucket, key,
                                     self.headers, self.srv.kms)
    return enc.encrypt(payload), \
        csse.response_headers(mp.user_defined)

def _complete_multipart(self, bucket, key, query, payload):
    uid = query["uploadId"][0]
    try:
        root = ET.fromstring(payload)
    except ET.ParseError as e:
        raise S3Error("MalformedXML") from e
    ns = f"{{{S3_NS}}}"
    parts = []
    for p in root.findall(f"{ns}Part") + root.findall("Part"):
        num = p.findtext(f"{ns}PartNumber") or \
            p.findtext("PartNumber")
        etag = p.findtext(f"{ns}ETag") or p.findtext("ETag") or ""
        if num is None or not num.isdigit():
            raise S3Error("MalformedXML")
        parts.append((int(num), etag.strip('"')))
    # SSE needs no extra bookkeeping here: the part table committed
    # atomically with xl.meta carries per-part ciphertext sizes
    # (each part is its own DARE stream; ObjectInfo.parts)
    # memory-governor admission: assembly holds AT MOST ONE part in
    # memory at a time (the erasure layer commits staged part files by
    # rename; the FS/gateway layers read part-by-part) — charge the
    # LARGEST part, never the object total, or a multipart object
    # bigger than the watermark could never complete (memgov.py)
    from ..utils.memgov import GOVERNOR
    try:
        staged = max((p.size for p in self.srv.layer.list_object_parts(
            bucket, key, uid)), default=0)
    except Exception:  # noqa: BLE001 — unknown upload: the layer call
        staged = 0     # below raises the proper S3 error
    # hard-quota gate BEFORE assembly fan-out: the staged parts were
    # already charged to the in-flight delta at upload time, so the
    # incoming size here is 0 — the check rejects a complete that
    # would SEAL a bucket already past its quota, without double-
    # counting the parts
    self._check_quota(bucket, 0)
    with GOVERNOR.charge(staged, "multipart"):
        oi = self.srv.layer.complete_multipart_upload(bucket, key, uid,
                                                      parts)
    out = ET.Element("CompleteMultipartUploadResult", xmlns=S3_NS)
    ET.SubElement(out, "Location").text = \
        f"{self.srv.endpoint}/{bucket}/{key}"
    ET.SubElement(out, "Bucket").text = bucket
    ET.SubElement(out, "Key").text = key
    ET.SubElement(out, "ETag").text = f'"{oi.etag}"'
    hdrs = {}
    if oi.version_id:
        hdrs["x-amz-version-id"] = oi.version_id
    self.srv.notify("s3:ObjectCreated:CompleteMultipartUpload", bucket,
               oi)
    self.srv.replicate(bucket, oi)
    self._send(200, _xml(out), headers=hdrs)

def _list_parts(self, bucket, key, query):
    uid = query["uploadId"][0]
    parts = self.srv.layer.list_object_parts(bucket, key, uid)
    root = ET.Element("ListPartsResult", xmlns=S3_NS)
    ET.SubElement(root, "Bucket").text = bucket
    ET.SubElement(root, "Key").text = key
    ET.SubElement(root, "UploadId").text = uid
    ET.SubElement(root, "IsTruncated").text = "false"
    for p in parts:
        pe = ET.SubElement(root, "Part")
        ET.SubElement(pe, "PartNumber").text = str(p.part_number)
        ET.SubElement(pe, "ETag").text = f'"{p.etag}"'
        ET.SubElement(pe, "Size").text = str(p.size)
    self._send(200, _xml(root))

# -- streaming PUT (cmd/erasure-encode.go block pipeline over the
# socket: body is never buffered; 5 GiB single PUT works in
# O(batch) memory) ------------------------------------------------

def _try_stream_put(self, path, bucket, key, query) -> bool:
    """Route large plain object PUTs / part uploads through the
    streaming pipeline.  Returns True when the request was fully
    handled (success or error); False falls back to the buffered
    path WITHOUT having consumed any body bytes."""
    if self.command != "PUT" or not bucket or not key:
        return False
    if path.startswith("/minio-tpu/") or bucket == "minio-tpu" \
            or not _BUCKET_RE.match(bucket):
        return False
    if any(q in query for q in ("tagging", "retention",
                                "legal-hold", "acl")):
        return False
    if "x-amz-copy-source" in self.headers:
        return False
    cl_hdr = self.headers.get("Content-Length")
    if cl_hdr is None:
        return False
    try:
        cl = int(cl_hdr)
    except ValueError:
        return False
    if cl <= STREAM_PUT_THRESHOLD:
        return False
    try:
        if cl > MAX_PUT_SIZE:
            raise S3Error("EntityTooLarge")
        # only layers with a REAL streaming override may take
        # this route — the ObjectLayer default would buffer the
        # whole body, bypassing max_body_size
        if type(self.srv.layer).put_object_stream \
                is ol.ObjectLayer.put_object_stream:
            if cl > self.srv.max_body_size:
                raise S3Error("EntityTooLarge")
            return False
        # SSE and transparent compression transform the body and
        # are not streamed yet: those bodies take the buffered
        # path (bounded by max_body_size)
        from ..crypto import sse as csse
        if "uploadId" in query:
            try:
                mp = self.srv.layer.get_multipart_info(
                    bucket, key, query["uploadId"][0])
                transforming = csse.is_encrypted(mp.user_defined)
            except Exception:  # noqa: BLE001 — invalid upload id
                return False   # buffered path raises it properly
        else:
            transforming = bool(csse.requested_sse(
                self.headers, self._bucket_sse_algo(bucket))) \
                or self._compression_eligible(key, cl)
        if transforming:
            if cl > self.srv.max_body_size:
                raise S3Error("EntityTooLarge")
            return False
    except S3Error as e:
        self._fail(e, path)
        self.close_connection = True
        return True
    # committed to streaming from here: any failure must be
    # answered in-line and the (half-read) connection dropped
    try:
        reader = self._auth_stream(path, query)
        self._rx_bytes = cl
        from ..admin.metrics import GLOBAL as mtr
        mtr.inc("mt_s3_rx_bytes_total", value=cl)
        if "uploadId" in query:
            self._stream_upload_part(bucket, key, query, reader,
                                     cl)
        else:
            self._stream_put_object(bucket, key, reader, cl)
    except Exception as e:  # noqa: BLE001 — XML like dispatch
        self._fail(e, path)
        self.close_connection = True
    return True

def _compression_eligible(self, key: str, size: int) -> bool:
    from .. import compress as mtc
    if self.srv.config.get("compression", "enable") != "on":
        return False
    exts = [e for e in self.srv.config.get(
        "compression", "extensions").split(",") if e]
    types = [t for t in self.srv.config.get(
        "compression", "mime_types").split(",") if t]
    ct = self.headers.get("Content-Type", "")
    return mtc.is_compressible(key, ct, size, exts, types)

def _auth_stream(self, path, query):
    """Authenticate a PUT without buffering its body; returns the
    verified body reader (signature first, digests checked at
    EOF before the object layer commits)."""
    self._query_token = query.get("X-Amz-Security-Token", [""])[0]
    cl = int(self.headers["Content-Length"])
    hdrs = {k: v for k, v in self.headers.items()}
    lookup = self.srv.iam.lookup_secret
    md5_hdr = self.headers.get("Content-MD5")
    want_md5 = None
    if md5_hdr:
        import base64
        try:
            want_md5 = base64.b64decode(md5_hdr)
        except Exception as e:
            raise S3Error("InvalidDigest") from e
    sha = self.headers.get("x-amz-content-sha256")
    try:
        if "Authorization" not in hdrs and \
                "X-Amz-Signature" not in query and \
                not ("Signature" in query and
                     "AWSAccessKeyId" in query):
            self.access_key = ""
            body = _BodyReader(
                self.rfile, cl,
                sha256_hex=(sha if sha and
                            sha != sigv4.UNSIGNED_PAYLOAD
                            else None),
                md5_digest=want_md5)
        elif hdrs.get("Authorization", "").startswith("AWS "):
            from . import sigv2
            self.access_key = sigv2.verify_request(
                lookup, self.command, path, query, hdrs)
            body = _BodyReader(self.rfile, cl,
                               md5_digest=want_md5)
        elif "Signature" in query and "AWSAccessKeyId" in query:
            from . import sigv2
            self.access_key = sigv2.verify_presigned(
                lookup, self.command, path, query, hdrs)
            body = _BodyReader(self.rfile, cl,
                               md5_digest=want_md5)
        elif "X-Amz-Signature" in query:
            self.access_key = sigv4.verify_presigned(
                lookup, self.command, path, query, hdrs,
                region=self.srv.region)
            body = _BodyReader(self.rfile, cl,
                               md5_digest=want_md5)
        elif sha == sigv4.STREAMING_PAYLOAD:
            self.access_key, key, seed, amz_date, scope = \
                sigv4.verify_request_streaming(
                    lookup, self.command, path, query, hdrs,
                    region=self.srv.region)
            framed = _BodyReader(self.rfile, cl)
            body = sigv4.ChunkedStreamReader(framed, key, seed,
                                             amz_date, scope)
            if want_md5 is not None:
                body = _MD5Reader(body, want_md5)
        else:
            sha_eff = sha or sigv4.UNSIGNED_PAYLOAD
            self.access_key = sigv4.verify_request(
                lookup, self.command, path, query, hdrs, sha_eff,
                region=self.srv.region)
            body = _BodyReader(
                self.rfile, cl,
                sha256_hex=(sha_eff
                            if sha_eff != sigv4.UNSIGNED_PAYLOAD
                            else None),
                md5_digest=want_md5)
    except sigv4.SigV4Error as e:
        raise S3Error(e.code) from e
    self._check_session_token()
    return body

def _stream_put_object(self, bucket, key, reader, cl: int):
    self._allow(iampol.PUT_OBJECT, f"{bucket}/{key}")
    user_defined = {}
    ct = self.headers.get("Content-Type")
    if ct:
        user_defined["content-type"] = ct
    for h, v in self.headers.items():
        if h.lower().startswith("x-amz-meta-"):
            user_defined[h.lower()] = v
    user_defined.update(self._tagging_header_meta())
    user_defined.update(self._lock_headers(bucket, key))
    self._check_quota(bucket, cl)
    versioned = self.srv.bucket_meta.versioning_enabled(bucket)
    tiered_ud = None if versioned else \
        self._tiered_meta_of(bucket, key, "", False)
    oi = self.srv.layer.put_object_stream(
        bucket, key, reader,
        ol.PutObjectOptions(
            user_defined=user_defined, versioned=versioned,
            parity=self._storage_class_parity(user_defined)))
    if tiered_ud is not None:
        self.srv.transition.delete_tiered(tiered_ud)
    self._charge_quota_usage(bucket, oi.size)
    hdrs = {"ETag": f'"{oi.etag}"'}
    if oi.version_id:
        hdrs["x-amz-version-id"] = oi.version_id
    self.srv.notify("s3:ObjectCreated:Put", bucket, oi)
    self.srv.replicate(bucket, oi)
    self._send(200, headers=hdrs)

def _stream_upload_part(self, bucket, key, query, reader,
                        cl: int):
    self._allow(iampol.PUT_OBJECT, f"{bucket}/{key}")
    uid = query["uploadId"][0]
    try:
        part_num = int(query["partNumber"][0])
    except (KeyError, ValueError) as e:
        raise S3Error("InvalidArgument") from e
    self._check_quota(bucket, cl)
    pi = self.srv.layer.put_object_part(bucket, key, uid, part_num,
                                   reader)
    self._charge_quota_usage(bucket, pi.size)
    self._send(200, headers={"ETag": f'"{pi.etag}"'})

def _put_object(self, bucket, key, query, payload):
    if "Content-Length" not in self.headers:
        raise S3Error("MissingContentLength")
    if len(payload) > MAX_OBJECT_SIZE:
        raise S3Error("EntityTooLarge")
    md5_hdr = self.headers.get("Content-MD5")
    if md5_hdr:
        import base64
        try:
            want = base64.b64decode(md5_hdr)
        except Exception as e:
            raise S3Error("InvalidDigest") from e
        if hashlib.md5(payload).digest() != want:
            raise S3Error("BadDigest")
    user_defined = {}
    ct = self.headers.get("Content-Type")
    if ct:
        user_defined["content-type"] = ct
    for h, v in self.headers.items():
        if h.lower().startswith("x-amz-meta-"):
            user_defined[h.lower()] = v
    user_defined.update(self._tagging_header_meta())
    oi, hdrs = self._store_object(bucket, key, payload,
                                  user_defined,
                                  "s3:ObjectCreated:Put")
    self._send(200, headers=hdrs)

def _store_object(self, bucket, key, payload, user_defined,
                  event_name):
    """Shared tail of every simple write path (PUT and POST
    policy): quota, compression, SSE, lock defaults, store,
    notify, replicate.  Returns (oi, response_headers)."""
    user_defined.update(self._lock_headers(bucket, key))
    self._check_quota(bucket, len(payload))
    versioned = self.srv.bucket_meta.versioning_enabled(bucket)
    # unversioned overwrite replaces the null version: remember
    # its tiered bytes, freed only AFTER the new write commits
    # (an early free would destroy data if this PUT fails)
    tiered_ud = None if versioned else \
        self._tiered_meta_of(bucket, key, "", False)
    from ..crypto import sse as csse
    payload = self._compress_for_put(key, user_defined, payload)
    enc = self._sse_for_put(bucket, key, user_defined)
    if enc is not None:
        payload = enc.encrypt(payload)
    oi = self.srv.layer.put_object(
        bucket, key, payload,
        ol.PutObjectOptions(
            user_defined=user_defined, versioned=versioned,
            parity=self._storage_class_parity(user_defined)))
    if tiered_ud is not None:
        self.srv.transition.delete_tiered(tiered_ud)
    self._charge_quota_usage(bucket, oi.size)
    hdrs = {"ETag": f'"{oi.etag}"'}
    hdrs.update(csse.response_headers(user_defined))
    if oi.version_id:
        hdrs["x-amz-version-id"] = oi.version_id
    self.srv.notify(event_name, bucket, oi)
    self.srv.replicate(bucket, oi)
    return oi, hdrs

# -- CopyObject / UploadPartCopy (cmd/object-handlers.go:886,
# cmd/object-multipart-handlers.go CopyObjectPartHandler) ----------

def _parse_copy_source(self) -> tuple[str, str, str | None]:
    """x-amz-copy-source -> (bucket, key, version_id).  The
    versionId qualifier is split off the RAW header first — a
    percent-encoded '?' inside the key must stay part of the key."""
    raw = self.headers.get("x-amz-copy-source", "")
    vid = None
    if "?versionId=" in raw:
        raw, vid = raw.split("?versionId=", 1)
        if vid == "null":
            vid = ""
    src = urllib.parse.unquote(raw).lstrip("/")
    if "/" not in src:
        raise S3Error("InvalidCopySource")
    sbucket, skey = src.split("/", 1)
    if not sbucket or not skey:
        raise S3Error("InvalidCopySource")
    return sbucket, skey, vid

def _read_copy_source(self, offset: int = 0, length: int = -1
                      ) -> tuple["ol.ObjectInfo", bytes, int]:
    """Fetch (and decrypt, honoring copy-source SSE-C headers) the
    copy source; returns (info, plaintext, plaintext_size)."""
    from ..crypto import sse as csse
    sbucket, skey, svid = self._parse_copy_source()
    self._allow(iampol.GET_OBJECT, f"{sbucket}/{skey}")
    opts = ol.ObjectOptions(version_id=svid)
    soi = self.srv.layer.get_object_info(sbucket, skey, opts)
    from ..objectlayer import tiering as _tr
    if _tr.is_transitioned(soi.user_defined) and \
            not _tr.restore_valid(soi.user_defined):
        # archived source: copying the stub would silently write
        # a 0-byte destination
        raise S3Error("InvalidObjectState")
    # conditional copy headers (checkCopyObjectPreconditions) —
    # checked on metadata alone, BEFORE any data is read
    if_match = self.headers.get("x-amz-copy-source-if-match")
    if_none = self.headers.get("x-amz-copy-source-if-none-match")
    if if_match and if_match.strip('"') != soi.etag:
        raise S3Error("PreconditionFailed")
    if if_none and if_none.strip('"') == soi.etag:
        raise S3Error("PreconditionFailed")
    from .. import compress as mtc
    compressed = mtc.META_COMPRESSION in soi.user_defined
    if csse.is_encrypted(soi.user_defined):
        enc = csse.ObjectEncryption.open(
            soi.user_defined, sbucket, skey, self.headers,
            self.srv.kms, copy_source=True)
        if not compressed:
            size = csse.decrypted_size(soi.user_defined, soi.size,
                                       soi.parts)
            data = csse.decrypt_object_range(
                enc, soi.user_defined, soi.size,
                lambda o, n: self.srv.layer.get_object(
                    sbucket, skey, o, n, opts)[1], offset, length,
                soi.parts)
            return soi, data, size
        inner = csse.decrypt_object_range(
            enc, soi.user_defined, soi.size,
            lambda o, n: self.srv.layer.get_object(
                sbucket, skey, o, n, opts)[1], 0, -1, soi.parts)
    elif not compressed:
        size = soi.size
        _, data = self.srv.layer.get_object(sbucket, skey, offset,
                                       length, opts)
        return soi, data, size
    else:
        _, inner = self.srv.layer.get_object(sbucket, skey, 0, -1,
                                        opts)
    full = mtc.decompress_stream(inner)
    data = full[offset:] if length < 0 \
        else full[offset:offset + length]
    return soi, data, len(full)

def _copy_object(self, bucket, key, query):
    from ..crypto import sse as csse
    sbucket, skey, svid = self._parse_copy_source()
    soi, data, _ = self._read_copy_source()
    directive = self.headers.get("x-amz-metadata-directive",
                                 "COPY")
    user_defined: dict[str, str] = {}
    if directive == "REPLACE":
        ct = self.headers.get("Content-Type")
        if ct:
            user_defined["content-type"] = ct
        for h, v in self.headers.items():
            if h.lower().startswith("x-amz-meta-"):
                user_defined[h.lower()] = v
    else:
        user_defined = {
            k: v for k, v in soi.user_defined.items()
            if k.startswith("x-amz-meta-") or k == "content-type"}
    tag_directive = self.headers.get("x-amz-tagging-directive",
                                     "COPY")
    if tag_directive == "REPLACE":
        user_defined.update(self._tagging_header_meta())
    elif soi.user_defined.get(self.TAG_KEY):
        user_defined[self.TAG_KEY] = soi.user_defined[self.TAG_KEY]
    user_defined.update(self._lock_headers(bucket, key))
    data = self._compress_for_put(key, user_defined, data)
    enc = self._sse_for_put(bucket, key, user_defined)
    sse_changed = enc is not None or \
        csse.is_encrypted(soi.user_defined)
    if sbucket == bucket and skey == key and svid is None and \
            directive != "REPLACE" and not sse_changed:
        raise S3Error("InvalidCopyDest")
    self._check_quota(bucket, len(data))
    if enc is not None:
        data = enc.encrypt(data)
    versioned = self.srv.bucket_meta.versioning_enabled(bucket)
    oi = self.srv.layer.put_object(
        bucket, key, data,
        ol.PutObjectOptions(
            user_defined=user_defined, versioned=versioned,
            parity=self._storage_class_parity(user_defined)))
    self._charge_quota_usage(bucket, oi.size)
    root = ET.Element("CopyObjectResult", xmlns=S3_NS)
    ET.SubElement(root, "ETag").text = f'"{oi.etag}"'
    ET.SubElement(root, "LastModified").text = _iso_date(oi.mod_time)
    hdrs = dict(csse.response_headers(user_defined))
    if oi.version_id:
        hdrs["x-amz-version-id"] = oi.version_id
    if svid is not None:
        hdrs["x-amz-copy-source-version-id"] = svid or "null"
    self.srv.notify("s3:ObjectCreated:Copy", bucket, oi)
    self.srv.replicate(bucket, oi)
    self._send(200, _xml(root), headers=hdrs)

def _upload_part_copy(self, bucket, key, query):
    uid = query["uploadId"][0]
    try:
        part_num = int(query["partNumber"][0])
    except (KeyError, ValueError) as e:
        raise S3Error("InvalidArgument") from e
    offset, length = 0, -1
    crng = self.headers.get("x-amz-copy-source-range")
    if crng:
        offset, length = _parse_range(crng)
        if offset < 0:
            raise S3Error("InvalidRange")
    _, data, _ = self._read_copy_source(offset, length)
    self._check_quota(bucket, len(data))
    data, _ = self._encrypt_part(bucket, key, uid, data)
    pi = self.srv.layer.put_object_part(bucket, key, uid, part_num,
                                   data)
    self._charge_quota_usage(bucket, pi.size)
    root = ET.Element("CopyPartResult", xmlns=S3_NS)
    ET.SubElement(root, "ETag").text = f'"{pi.etag}"'
    ET.SubElement(root, "LastModified").text = \
        _iso_date(pi.mod_time or 0)
    self._send(200, _xml(root))

def _lock_headers(self, bucket: str, key: str) -> dict[str, str]:
    """Explicit x-amz-object-lock-* headers, else the bucket's
    default retention (cmd/bucket-object-lock.go)."""
    from ..bucket import objectlock as olock
    raw = self.srv.bucket_meta.get_config(bucket, "object-lock")
    out: dict[str, str] = {}
    mode = self.headers.get(olock.AMZ_OBJECT_LOCK_MODE)
    until = self.headers.get(olock.AMZ_OBJECT_LOCK_RETAIN_UNTIL)
    hold = self.headers.get(olock.AMZ_OBJECT_LOCK_LEGAL_HOLD)
    if mode or until or hold:
        if raw is None:
            raise S3Error("InvalidRequest")
        if (mode is None) != (until is None):
            raise S3Error("InvalidRequest")
        if mode:
            if mode not in (olock.GOVERNANCE, olock.COMPLIANCE):
                raise S3Error("InvalidRequest")
            # the retain-until header must be a valid, future
            # timestamp — storing garbage would mint an object the
            # client believes is WORM but that active() never locks
            try:
                dt = datetime.datetime.fromisoformat(
                    until.replace("Z", "+00:00"))
                if dt.tzinfo is None:
                    dt = dt.replace(tzinfo=datetime.timezone.utc)
            except ValueError as e:
                raise S3Error("InvalidRequest") from e
            if dt <= datetime.datetime.now(datetime.timezone.utc):
                raise S3Error("InvalidRequest")
            out[olock.AMZ_OBJECT_LOCK_MODE] = mode
            out[olock.AMZ_OBJECT_LOCK_RETAIN_UNTIL] = \
                dt.astimezone(datetime.timezone.utc).strftime(
                    "%Y-%m-%dT%H:%M:%SZ")
        if hold:
            if hold not in ("ON", "OFF"):
                raise S3Error("InvalidRequest")
            out[olock.AMZ_OBJECT_LOCK_LEGAL_HOLD] = hold
        return out
    if raw is not None:
        cfg = _try(lambda: olock.LockConfig.parse(raw.encode()))
        out.update(cfg.default_retention_headers())
    return out

def _get_object(self, bucket, key, query, head: bool):
    q1 = {k: v[0] for k, v in query.items()}
    vid = q1.get("versionId")
    if vid == "null":
        vid = ""
    opts = ol.ObjectOptions(version_id=vid)
    from ..crypto import sse as csse
    rng = self.headers.get("Range")
    offset, length = 0, -1
    sse_hdrs: dict[str, str] = {}
    plain_size: int | None = None
    from .. import compress as mtc
    try:
        oi_pre = None
        if any(h in self.headers for h in
               ("If-Match", "If-None-Match", "If-Modified-Since",
                "If-Unmodified-Since")):
            # preconditions run on metadata BEFORE any data read
            # — a 304 revalidation must not decode the object
            oi_pre = self.srv.layer.get_object_info(bucket, key, opts)
            if not oi_pre.delete_marker and \
                    self._preconditions_304(oi_pre):
                return self._send(
                    304, b"",
                    headers={"ETag":
                             f'"{self._display_etag(oi_pre)}"',
                             "Last-Modified":
                             _http_date(oi_pre.mod_time)},
                    content_length=0)
        body_gen = None    # streaming plain-object body
        if rng:
            offset, length = _parse_range(rng)
        if head or rng:
            # metadata first: a range is in client (decompressed/
            # decrypted) space — fetching stored bytes at those
            # offsets would decode data that gets thrown away
            oi = oi_pre if oi_pre is not None else \
                self.srv.layer.get_object_info(bucket, key, opts)
            data = None
            from ..objectlayer import tiering as _tchk
            if rng and not head and \
                    _tchk.is_transitioned(oi.user_defined) and \
                    not _tchk.restore_valid(oi.user_defined):
                # archived stub: 403 before the size-0 range
                # fetch can 416
                raise S3Error("InvalidObjectState")
            if rng and not oi.delete_marker and \
                    mtc.META_COMPRESSION not in oi.user_defined \
                    and not csse.is_encrypted(oi.user_defined):
                # plain ranged GET: only covering blocks are read
                # and the body streams (erasure-decode.go:229-246)
                oi, body_gen = self.srv.layer.get_object_reader(
                    bucket, key, offset, length, opts)
        else:
            # full GET: reader returns metadata + a body stream;
            # transform paths (SSE/compression) materialize below
            oi, body_gen = self.srv.layer.get_object_reader(
                bucket, key, 0, -1, opts)
            data = None
        if not head and oi.delete_marker:
            raise ol.MethodNotAllowed(key)
        from ..objectlayer import tiering
        archived = tiering.is_transitioned(oi.user_defined)
        stubbed = archived and \
            not tiering.restore_valid(oi.user_defined)
        if stubbed and not head:
            # data lives in the tier: GET needs a restore first
            # (cmd/object-handlers.go InvalidObjectState)
            raise S3Error("InvalidObjectState")
        encrypted = csse.is_encrypted(oi.user_defined) and \
            not oi.delete_marker and not stubbed
        compressed = mtc.META_COMPRESSION in oi.user_defined and \
            not oi.delete_marker and not stubbed
        if body_gen is not None and (encrypted or compressed):
            # transform paths need the stored bytes in hand
            data = b"".join(body_gen)
            body_gen = None
        if stubbed:
            # HEAD of the stub reports the archived identity
            plain_size = int(oi.user_defined.get(
                tiering.META_SIZE, "0"))
        inner: bytes | None = None
        if encrypted:
            # DecryptObjectInfo: the data path reads only covering
            # DARE packages (full stream when also compressed)
            enc = csse.ObjectEncryption.open(
                oi.user_defined, bucket, key, self.headers,
                self.srv.kms)
            inner_size = csse.decrypted_size(
                oi.user_defined, oi.size, oi.parts)
            sse_hdrs = csse.response_headers(oi.user_defined)
            if not compressed:
                plain_size = inner_size
                if rng and offset >= plain_size:
                    raise S3Error("InvalidRange")
            if not head:
                if data is not None and not rng and \
                        len(data) == oi.size:
                    blob = data       # full ciphertext in hand

                    def read(o, n, _b=blob):
                        return _b[o:o + n]
                else:
                    def read(o, n):
                        return self.srv.layer.get_object(
                            bucket, key, o, n, opts)[1]
                if compressed:
                    inner = csse.decrypt_object_range(
                        enc, oi.user_defined, oi.size, read,
                        0, -1, oi.parts)
                else:
                    data = csse.decrypt_object_range(
                        enc, oi.user_defined, oi.size, read,
                        offset, length, oi.parts)
        if compressed:
            if head:
                plain_size = int(
                    oi.user_defined[csse.META_ACTUAL_SIZE])
            else:
                if inner is None:
                    if data is not None and not rng and \
                            len(data) == oi.size:
                        inner = data
                    else:
                        _, inner = self.srv.layer.get_object(
                            bucket, key, 0, -1, opts)
                full = mtc.decompress_stream(inner)
                plain_size = len(full)
                if rng and offset >= plain_size:
                    raise S3Error("InvalidRange")
                data = full[offset:] if length < 0 \
                    else full[offset:offset + length]
    except ol.MethodNotAllowed:
        # delete marker (cmd/object-handlers.go: 405 + header)
        return self._send(
            405, s3err.to_xml(s3err.get("MethodNotAllowed")),
            headers={"x-amz-delete-marker": "true"})
    entity_size = plain_size if plain_size is not None else oi.size
    hdrs = {
        "ETag": f'"{oi.etag}"',
        "Last-Modified": _http_date(oi.mod_time),
        "Accept-Ranges": "bytes",
    }
    if archived:
        from ..objectlayer import tiering as _tr
        hdrs["ETag"] = \
            f'"{oi.user_defined.get(_tr.META_ETAG, oi.etag)}"'
        hdrs[_tr.STORAGE_CLASS_HDR] = oi.user_defined.get(
            _tr.STORAGE_CLASS_HDR, "")
        rh = _tr.restore_header(oi.user_defined)
        if rh:
            hdrs[_tr.RESTORE_HDR] = rh
    elif oi.user_defined.get("x-amz-storage-class"):
        # RRS objects report their class (AWS omits STANDARD)
        hdrs["x-amz-storage-class"] = \
            oi.user_defined["x-amz-storage-class"]
    hdrs.update(sse_hdrs)
    # hot-read plane attribution (objectlayer/hotread.py): bodies the
    # plane served carry how — ``hit`` (validated cache), ``coalesced``
    # (shared another reader's in-flight decode) or ``miss`` (led the
    # flight) — so clients and the bench can see coalescing work
    cache_status = getattr(body_gen, "cache_status", "")
    if cache_status:
        hdrs["x-minio-tpu-cache"] = cache_status
    if oi.version_id:
        hdrs["x-amz-version-id"] = oi.version_id
    for k2, v in oi.user_defined.items():
        if k2.startswith("x-amz-meta-"):
            hdrs[k2] = v
    ct = oi.content_type or "binary/octet-stream"
    tag_hdr = oi.user_defined.get(self.TAG_KEY)
    if tag_hdr:
        hdrs["x-amz-tagging-count"] = str(
            len(urllib.parse.parse_qsl(tag_hdr,
                                       keep_blank_values=True)))
    self.srv.notify("s3:ObjectAccessed:Head" if head
               else "s3:ObjectAccessed:Get", bucket, oi)
    if head:
        if oi.delete_marker:
            hdrs = {"x-amz-delete-marker": "true"}
            if oi.version_id:
                hdrs["x-amz-version-id"] = oi.version_id
            return self._send(405, b"", headers=hdrs,
                              content_length=0)
        return self._send(200, b"", content_type=ct, headers=hdrs,
                          content_length=entity_size)
    if rng:
        if body_gen is not None:
            start = max(0, entity_size + offset) if offset < 0 \
                else offset
            sent = entity_size - start if length < 0 \
                else min(length, entity_size - start)
            hdrs["Content-Range"] = \
                f"bytes {start}-{start + sent - 1}/{entity_size}"
            return self._send_stream(206, body_gen, sent, ct,
                                     hdrs)
        start = entity_size - len(data) if offset < 0 else offset
        hdrs["Content-Range"] = \
            f"bytes {start}-{start + len(data) - 1}/{entity_size}"
        return self._send(206, data, content_type=ct, headers=hdrs)
    if body_gen is not None:
        return self._send_stream(200, body_gen, entity_size, ct,
                                 hdrs)
    return self._send(200, data, content_type=ct, headers=hdrs)

def _storage_class_parity(self, user_defined: dict) -> int | None:
    """x-amz-storage-class -> parity override via the
    storage_class config subsystem (cmd/config/storageclass
    applied at cmd/erasure-object.go:631).  Also records RRS in
    metadata so HEAD reports it (AWS omits STANDARD)."""
    sc = self.headers.get("x-amz-storage-class", "").upper()
    explicit = sc not in ("", "STANDARD")
    if not explicit:
        value = self.srv.config.get("storage_class", "standard")
    elif sc == "REDUCED_REDUNDANCY":
        value = self.srv.config.get("storage_class", "rrs")
    else:
        raise S3Error("InvalidStorageClass")
    n = _layer_set_drive_count(self.srv.layer)
    if not value or not n:
        return None
    from ..utils.kvconfig import parse_storage_class
    try:
        parity = parse_storage_class(value, n)
    except ValueError as e:
        if explicit:
            # the client asked for this class: tell them
            raise S3Error("InvalidStorageClass") from e
        # bad *config* must not fail clients who sent no header
        return None
    if explicit:
        user_defined["x-amz-storage-class"] = sc
    return parity

def _display_etag(self, oi) -> str:
    """The etag clients see: archived stubs advertise the
    original object's etag (META_ETAG), not the stub's."""
    from ..objectlayer import tiering as _tr
    if _tr.is_transitioned(oi.user_defined):
        return oi.user_defined.get(_tr.META_ETAG, oi.etag)
    return oi.etag

def _preconditions_304(self, oi) -> bool:
    """Evaluate GET/HEAD preconditions (checkPreconditions,
    cmd/object-handlers-common.go).  Raises 412 for failed
    If-Match/If-Unmodified-Since; returns True when the response
    must be 304 Not Modified."""
    if_match = self.headers.get("If-Match")
    if_none = self.headers.get("If-None-Match")
    if_mod = self.headers.get("If-Modified-Since")
    if_unmod = self.headers.get("If-Unmodified-Since")
    etag = self._display_etag(oi)
    # Last-Modified is second-granularity: compare truncated
    # seconds or an echoed header spuriously fails
    mod_s = oi.mod_time // 10 ** 9

    def etag_in(header: str) -> bool:
        tags = [t.strip().strip('"') for t in header.split(",")]
        return "*" in tags or etag in tags

    def parse_date(v: str) -> float | None:
        try:
            return email.utils.parsedate_to_datetime(v).timestamp()
        except (TypeError, ValueError):
            return None         # invalid dates are ignored

    if if_match is not None and not etag_in(if_match):
        raise S3Error("PreconditionFailed")
    if if_match is None and if_unmod is not None:
        t = parse_date(if_unmod)
        if t is not None and mod_s > t:
            raise S3Error("PreconditionFailed")
    if if_none is not None and etag_in(if_none):
        return True
    if if_none is None and if_mod is not None:
        t = parse_date(if_mod)
        if t is not None and mod_s <= t:
            return True
    return False

def _restore_object(self, bucket, key, query, payload):
    """PostRestoreObjectHandler: <RestoreRequest><Days>N</Days>
    </RestoreRequest> copies tiered bytes back for N days."""
    from ..objectlayer import tiering
    days = 1
    if payload:
        try:
            root = ET.fromstring(payload)
            for el in root.iter():
                if el.tag.split("}")[-1] == "Days":
                    days = int(el.text or 1)
        except (ET.ParseError, ValueError) as e:
            raise S3Error("MalformedXML") from e
    if days < 1:
        raise S3Error("InvalidArgument")
    vid = query.get("versionId", [None])[0]
    if vid == "null":
        vid = ""                # explicit null version
    ts = self.srv.transition
    try:
        fresh = ts.restore(bucket, key, days, version_id=vid)
    except tiering.TierError as e:
        # only "not archived" is the client's mistake; a tier
        # backend failure is a server-side problem, not a 403
        if "archived state" in str(e):
            raise S3Error("InvalidObjectState") from e
        raise S3Error("InternalError") from e
    oi = self.srv.layer.get_object_info(
        bucket, key, ol.ObjectOptions(version_id=vid))
    self.srv.notify("s3:ObjectRestore:Completed", bucket, oi)
    # 202 while "in progress" (fresh copy), 200 when it already
    # held a valid restored copy (object-handlers.go semantics)
    return self._send(202 if fresh else 200, b"")

def _tiered_meta_of(self, bucket, key, vid, versioned):
    """Metadata of the version about to be removed/replaced, for
    freeing its tier bytes AFTER the destructive op commits.
    None when nothing tiered is at stake.  vid semantics follow
    the layer: None = latest, "" = null version."""
    if not self.srv.transition.tiers:
        return None
    if versioned and vid is None:
        return None         # delete-marker write keeps the data
    try:
        old = self.srv.layer.get_object_info(
            bucket, key, ol.ObjectOptions(version_id=vid))
    except ol.ObjectLayerError:
        return None
    from ..objectlayer import tiering as _tr
    return old.user_defined \
        if _tr.is_transitioned(old.user_defined) else None

def _delete_object(self, bucket, key, query):
    q1 = {k: v[0] for k, v in query.items()}
    vid = q1.get("versionId")
    if vid == "null":
        vid = ""
    self._check_retention(bucket, key, vid)
    versioned = self.srv.bucket_meta.versioning_enabled(bucket)
    tiered_ud = self._tiered_meta_of(bucket, key, vid, versioned)
    res = self.srv.layer.delete_object(
        bucket, key, ol.ObjectOptions(version_id=vid,
                                      versioned=versioned))
    if tiered_ud is not None:   # freed only after the commit
        self.srv.transition.delete_tiered(tiered_ud)
    hdrs = {}
    if res.delete_marker:
        hdrs["x-amz-delete-marker"] = "true"
    if res.version_id:
        hdrs["x-amz-version-id"] = res.version_id
    self.srv.notify("s3:ObjectRemoved:DeleteMarkerCreated"
               if res.delete_marker else "s3:ObjectRemoved:Delete",
               bucket, res)
    self.srv.replicate(bucket, res, delete=True)
    self._send(204, headers=hdrs)

def _check_retention(self, bucket, key, vid) -> None:
    """WORM enforcement: deleting a *specific version* under
    retention/legal hold is refused (a versioned delete that only
    writes a delete marker is always allowed)."""
    from ..bucket import objectlock as olock
    if vid is None:
        if self.srv.bucket_meta.versioning_enabled(bucket):
            return      # becomes a delete marker, data retained
    if self.srv.bucket_meta.get_config(bucket, "object-lock") is None:
        return
    try:
        oi = self.srv.layer.get_object_info(
            bucket, key, ol.ObjectOptions(version_id=vid))
    except ol.ObjectLayerError:
        return
    bypass = self._governance_bypass(f"{bucket}/{key}")
    if not olock.check_delete_allowed(oi.user_defined,
                                      governance_bypass=bypass):
        raise S3Error("ObjectLocked")


# handler methods _make_handler attaches to the request class
HANDLERS = [
    "_object_api", "_vid", "_object_tagging", "_object_retention",
    "_object_legal_hold", "_governance_bypass", "_select_object",
    "_fetch_plain_chunks", "_plain_size_estimate", "_check_quota",
    "_charge_quota_usage",
    "_bucket_sse_algo", "_sse_for_put",
    "_compress_for_put", "_tagging_header_meta", "_create_multipart",
    "_upload_part", "_encrypt_part", "_complete_multipart",
    "_list_parts", "_try_stream_put", "_compression_eligible",
    "_auth_stream", "_stream_put_object", "_stream_upload_part",
    "_put_object", "_store_object", "_parse_copy_source",
    "_read_copy_source", "_copy_object", "_upload_part_copy",
    "_lock_headers", "_get_object", "_storage_class_parity",
    "_display_etag", "_preconditions_304", "_restore_object",
    "_tiered_meta_of", "_delete_object", "_check_retention",
]
