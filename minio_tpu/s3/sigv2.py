"""AWS Signature Version 2 — verifier and signer
(cmd/signature-v2.go: doesSignV2Match, doesPresignV2SignatureMatch).

V2 signs a newline-joined string-to-sign with HMAC-SHA1:

    Method\\nContent-MD5\\nContent-Type\\nDate\\nCanonicalizedAmzHeaders
    CanonicalizedResource

where CanonicalizedResource is the path plus a fixed whitelist of
subresources in sorted order (cmd/signature-v2.go resourceList).
Presigned form carries AWSAccessKeyId/Expires/Signature query params and
substitutes Expires for the Date line.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time
import urllib.parse

from .sigv4 import SigV4Error as SigError

# cmd/signature-v2.go:66 resourceList — subresources included in the
# canonical resource, in sorted order
RESOURCE_LIST = [
    "accelerate", "acl", "cors", "delete", "encryption", "legal-hold",
    "lifecycle", "location", "logging", "notification", "partNumber",
    "policy", "requestPayment", "response-cache-control",
    "response-content-disposition", "response-content-encoding",
    "response-content-language", "response-content-type", "response-expires",
    "retention", "select", "select-type", "tagging", "torrent", "uploadId",
    "uploads", "versionId", "versioning", "versions", "website",
]


def canonicalized_amz_headers(headers: dict[str, str]) -> str:
    amz: dict[str, list[str]] = {}
    for k, v in headers.items():
        lk = k.lower().strip()
        if lk.startswith("x-amz-"):
            amz.setdefault(lk, []).append(v.strip())
    return "".join(f"{k}:{','.join(amz[k])}\n" for k in sorted(amz))


def canonicalized_resource(path: str, query: dict[str, list[str]]) -> str:
    out = path or "/"
    sub = []
    for k in sorted(query):
        if k in RESOURCE_LIST:
            v = query[k][0]
            sub.append(f"{k}={v}" if v else k)
    if sub:
        out += "?" + "&".join(sub)
    return out


def string_to_sign(method: str, path: str, query: dict[str, list[str]],
                   headers: dict[str, str], date_line: str) -> str:
    h = {k.lower(): v for k, v in headers.items()}
    return "\n".join([
        method.upper(),
        h.get("content-md5", ""),
        h.get("content-type", ""),
        date_line,
    ]) + "\n" + canonicalized_amz_headers(headers) \
        + canonicalized_resource(path, query)


def _signature(secret: str, sts: str) -> str:
    return base64.b64encode(
        hmac.new(secret.encode(), sts.encode(), hashlib.sha1)
        .digest()).decode()


def sign_header(access_key: str, secret_key: str, method: str, path: str,
                query: dict[str, list[str]],
                headers: dict[str, str]) -> str:
    """Returns the Authorization header value ``AWS AK:Signature``."""
    h = {k.lower(): v for k, v in headers.items()}
    date_line = "" if "x-amz-date" in h else h.get("date", "")
    sts = string_to_sign(method, path, query, headers, date_line)
    return f"AWS {access_key}:{_signature(secret_key, sts)}"


def presign(access_key: str, secret_key: str, method: str, path: str,
            expires_epoch: int,
            query: dict[str, list[str]] | None = None) -> str:
    """Returns the query string for a presigned V2 URL."""
    q = dict(query or {})
    sts = string_to_sign(method, path, q, {}, str(expires_epoch))
    q2 = {
        "AWSAccessKeyId": [access_key],
        "Expires": [str(expires_epoch)],
        "Signature": [_signature(secret_key, sts)],
    }
    q.update(q2)
    return urllib.parse.urlencode({k: v[0] for k, v in q.items()})


def verify_request(lookup_secret, method: str, path: str,
                   query: dict[str, list[str]],
                   headers: dict[str, str]) -> str:
    """Header-auth V2 (doesSignV2Match); returns the access key."""
    h = {k.lower(): v for k, v in headers.items()}
    auth = h.get("authorization", "")
    if not auth.startswith("AWS ") or ":" not in auth:
        raise SigError("AccessDenied", "malformed V2 Authorization")
    access_key, _, got_sig = auth[4:].strip().partition(":")
    secret = lookup_secret(access_key)
    if secret is None:
        raise SigError("InvalidAccessKeyId", "no such key")
    date_line = "" if "x-amz-date" in h else h.get("date", "")
    if not date_line and "x-amz-date" not in h:
        raise SigError("AccessDenied", "missing Date header")
    sts = string_to_sign(method, path, query, headers, date_line)
    want = _signature(secret, sts)
    if not hmac.compare_digest(want, got_sig):
        raise SigError("SignatureDoesNotMatch", "V2 signature mismatch")
    return access_key


def verify_presigned(lookup_secret, method: str, path: str,
                     query: dict[str, list[str]],
                     headers: dict[str, str] | None = None,
                     now: float | None = None) -> str:
    """Presigned V2 (doesPresignV2SignatureMatch); returns the access
    key.  ``headers`` participate in the string-to-sign (SDKs sign
    Content-Type / x-amz-* into presigned V2 URLs)."""
    try:
        access_key = query["AWSAccessKeyId"][0]
        expires = int(query["Expires"][0])
        got_sig = query["Signature"][0]
    except (KeyError, IndexError, ValueError) as e:
        raise SigError("AccessDenied", "malformed presigned V2 query") \
            from e
    if (now if now is not None else time.time()) > expires:
        raise SigError("AccessDenied", "request has expired")
    secret = lookup_secret(access_key)
    if secret is None:
        raise SigError("InvalidAccessKeyId", "no such key")
    rest = {k: v for k, v in query.items()
            if k not in ("AWSAccessKeyId", "Expires", "Signature")}
    sts = string_to_sign(method, path, rest, headers or {}, str(expires))
    want = _signature(secret, sts)
    if not hmac.compare_digest(want, got_sig):
        raise SigError("SignatureDoesNotMatch", "V2 signature mismatch")
    return access_key
