"""Browser web backend — JSON-RPC service + upload/download endpoints.

Reference: cmd/web-router.go:77-97 registers the JSON-RPC service
`web.*` (cmd/web-handlers.go, ~2.3k LoC) used by the React SPA:
Login issues a JWT, and the RPCs (ServerInfo, StorageInfo, MakeBucket,
DeleteBucket, ListBuckets, ListObjects, RemoveObject, PresignedGet,
CreateURLToken, GetAuth/GenerateAuth/SetAuth) plus raw upload/download/
zip endpoints drive the browser UI.  Routes here:

  POST /minio-tpu/webrpc                      JSON-RPC 2.0 envelope
  PUT  /minio-tpu/upload/<bucket>/<key>       Bearer JWT
  GET  /minio-tpu/download/<bucket>/<key>?token=JWT
  POST /minio-tpu/zip?token=JWT               {"bucketName","prefix","objects"}
  GET  /minio-tpu/browser                     single-file SPA (browser.html
                                              — the React app's role,
                                              browser/app/js)

Authorization mirrors the reference: Login validates credentials via
IAM, the JWT (HS256, signed with the root secret, cmd/jwt.go) carries
the access key, and each RPC re-checks the mapped S3 action through
IAMSys.IsAllowed (web-handlers.go authenticateRequest + IsAllowed).
"""

from __future__ import annotations

import hmac
import json
import re
import time
import urllib.parse
import zipfile

from ..iam.sts import STSError, sign_token, verify_token
from ..objectlayer import interface as oli

WEBRPC_PATH = "/minio-tpu/webrpc"
UPLOAD_PREFIX = "/minio-tpu/upload/"
DOWNLOAD_PREFIX = "/minio-tpu/download/"
ZIP_PATH = "/minio-tpu/zip"
BROWSER_PATH = "/minio-tpu/browser"
TOKEN_TTL_S = 24 * 3600            # cmd/jwt.go defaultJWTExpiry
UI_VERSION = "minio-tpu-web/1"


class WebError(Exception):
    def __init__(self, message: str, code: int = -32000):
        super().__init__(message)
        self.code = code


class AuthError(WebError):
    def __init__(self, message: str = "Authentication failed"):
        super().__init__(message, -32001)


def _mint(srv, access_key: str) -> str:
    return sign_token({"accessKey": access_key, "sub": access_key,
                       "iss": "web", "exp": int(time.time()) + TOKEN_TTL_S},
                      srv.iam.root.secret_key)


def _verify(srv, token: str) -> str:
    """Token -> authenticated access key."""
    if not token:
        raise AuthError("missing token")
    try:
        claims = verify_token(token, srv.iam.root.secret_key)
    except STSError as e:
        raise AuthError(str(e)) from e
    if claims.get("iss") != "web":
        raise AuthError("not a web token")
    ak = claims.get("accessKey") or claims.get("sub") or ""
    if srv.iam.lookup_secret(ak) is None:
        raise AuthError("unknown access key")
    return ak


def _allowed(srv, access_key: str, action: str, bucket: str,
             obj: str = "") -> None:
    # same resource convention as the S3 path (server.py _allow):
    # "bucket" or "bucket/key" — IAMSys.is_allowed's 4th arg is the
    # Condition context dict, never the object key
    resource = f"{bucket}/{obj}" if obj else bucket
    if not srv.iam.is_allowed(access_key, action, resource):
        raise AuthError("access denied")


class WebRPC:
    """The `web.*` method table (cmd/web-handlers.go webAPIHandlers)."""

    def __init__(self, srv):
        self.srv = srv
        self.started = time.time()

    # every method takes (access_key | None, params) and returns a dict
    def dispatch(self, method: str, params: dict, token: str) -> dict:
        name = method.split(".", 1)[-1]
        fn = getattr(self, f"rpc_{name}", None)
        if fn is None:
            raise WebError(f"unknown method {method}", -32601)
        if name == "Login":
            return fn(None, params)
        return fn(_verify(self.srv, token), params)

    # -- session -----------------------------------------------------------

    def rpc_Login(self, _ak, p: dict) -> dict:
        user = p.get("username", "")
        password = p.get("password", "")
        if not isinstance(user, str) or not isinstance(password, str):
            raise AuthError("Invalid credentials")
        secret = self.srv.iam.lookup_secret(user)
        if secret is None or not hmac.compare_digest(secret.encode(),
                                                     password.encode()):
            raise AuthError("Invalid credentials")
        u = self.srv.iam.get_user(user)   # exists: lookup_secret succeeded
        # STS temp credentials need their session token, not a password
        # login (web-handlers.go rejects them too)
        if getattr(u, "parent_user", "") and getattr(u, "expiration", 0):
            raise AuthError("Invalid credentials")
        return {"token": _mint(self.srv, user), "uiVersion": UI_VERSION}

    def rpc_CreateURLToken(self, ak, _p) -> dict:
        return {"token": _mint(self.srv, ak), "uiVersion": UI_VERSION}

    # -- server ------------------------------------------------------------

    def rpc_ServerInfo(self, ak, _p) -> dict:
        import platform
        return {
            "MinioVersion": "minio-tpu-dev",
            "MinioPlatform": f"{platform.system()} {platform.machine()}",
            "MinioRuntime": f"python {platform.python_version()}",
            "MinioGlobalInfo": {"isDistErasure": False,
                                "uptime_s": int(time.time() - self.started)},
            "uiVersion": UI_VERSION,
        }

    def rpc_StorageInfo(self, ak, _p) -> dict:
        used = 0
        if self.srv.usage is not None:
            try:
                used = getattr(self.srv.usage, 'objects_total_size', 0)
            except Exception:
                used = 0
        return {"used": used, "uiVersion": UI_VERSION}

    # -- buckets -----------------------------------------------------------

    def rpc_MakeBucket(self, ak, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        _allowed(self.srv, ak, "s3:CreateBucket", bucket)
        self.srv.layer.make_bucket(bucket)
        return {"uiVersion": UI_VERSION}

    def rpc_DeleteBucket(self, ak, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        _allowed(self.srv, ak, "s3:DeleteBucket", bucket)
        self.srv.layer.delete_bucket(bucket)
        self.srv.bucket_meta.drop(bucket)
        return {"uiVersion": UI_VERSION}

    def rpc_ListBuckets(self, ak, _p) -> dict:
        out = []
        for b in self.srv.layer.list_buckets():
            if self.srv.iam.is_allowed(ak, "s3:ListBucket", b.name):
                out.append({"name": b.name,
                            "creationDate": _iso(b.created)})
        return {"buckets": out, "uiVersion": UI_VERSION}

    def rpc_ListObjects(self, ak, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        prefix = p.get("prefix", "")
        marker = p.get("marker", "")
        _allowed(self.srv, ak, "s3:ListBucket", bucket)
        res = self.srv.layer.list_objects(bucket, prefix=prefix,
                                          marker=marker, delimiter="/",
                                          max_keys=1000)
        objects = [{"name": o.name, "size": o.size, "etag": o.etag,
                    "lastModified": _iso(o.mod_time),
                    "contentType": o.content_type} for o in res.objects]
        objects += [{"name": d, "size": 0, "lastModified": "",
                     "contentType": ""} for d in res.prefixes]
        return {"objects": objects, "istruncated": res.is_truncated,
                "nextmarker": res.next_marker, "writable": True,
                "uiVersion": UI_VERSION}

    def rpc_RemoveObject(self, ak, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        removed = []
        for obj in p.get("objects", []):
            _allowed(self.srv, ak, "s3:DeleteObject", bucket, obj)
            if obj.endswith("/"):      # prefix delete, as the UI offers
                # expanding a prefix is a listing: require ListBucket so
                # delete-only grants can't enumerate bucket contents
                _allowed(self.srv, ak, "s3:ListBucket", bucket)
                res = self.srv.layer.list_objects(bucket, prefix=obj,
                                                  max_keys=10 ** 6)
                for oi in res.objects:
                    self.srv.layer.delete_object(bucket, oi.name)
                    removed.append(oi.name)
            else:
                self.srv.layer.delete_object(bucket, obj)
                removed.append(obj)
        return {"removed": removed, "uiVersion": UI_VERSION}

    # -- sharing -----------------------------------------------------------

    def rpc_PresignedGet(self, ak, p: dict) -> dict:
        from .sigv4 import Credentials, presign_url
        bucket = p.get("bucketName", "")
        obj = p.get("objectName", "")
        expiry = int(p.get("expiry", 604800) or 604800)
        _allowed(self.srv, ak, "s3:GetObject", bucket, obj)
        secret = self.srv.iam.lookup_secret(ak)
        host = p.get("host") or f"127.0.0.1:{self.srv.port}"
        url = presign_url(
            Credentials(ak, secret), "GET",
            f"http://{host}/{bucket}/{urllib.parse.quote(obj)}",
            expiry, self.srv.region)
        return {"url": url, "uiVersion": UI_VERSION}

    # -- bucket policy kinds (web-handlers.go SetBucketPolicy /
    # GetBucketPolicy / ListAllBucketPolicies: the UI works in canned
    # kinds per bucket/prefix — none | readonly | writeonly | readwrite
    # — which expand to real bucket-policy statements) -------------------

    _KIND_ACTIONS = {
        "readonly": ("s3:GetObject",),
        "writeonly": ("s3:AbortMultipartUpload", "s3:DeleteObject",
                      "s3:PutObject"),
        "readwrite": ("s3:AbortMultipartUpload", "s3:DeleteObject",
                      "s3:GetObject", "s3:PutObject"),
    }

    def _policy_doc(self, bucket: str) -> dict:
        raw = self.srv.bucket_meta.get_config(bucket, "policy")
        if not raw:
            return {"Version": "2012-10-17", "Statement": []}
        return json.loads(raw)

    @staticmethod
    def _prefix_arn(bucket: str, prefix: str) -> str:
        return f"arn:aws:s3:::{bucket}/{prefix}*"

    def _kind_of(self, stmt: dict) -> str:
        acts = set(stmt.get("Action") or [])
        for kind, kacts in self._KIND_ACTIONS.items():
            if acts == set(kacts):
                return kind
        return "none" if not acts else "custom"

    def rpc_SetBucketPolicy(self, ak, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        prefix = p.get("prefix", "")
        kind = p.get("policy", "none")
        if kind not in ("none", *self._KIND_ACTIONS):
            raise WebError(f"invalid policy kind {kind!r}")
        _allowed(self.srv, ak, "s3:PutBucketPolicy", bucket)
        self.srv.layer.get_bucket_info(bucket)
        doc = self._policy_doc(bucket)
        arn = self._prefix_arn(bucket, prefix)
        doc["Statement"] = [s for s in doc.get("Statement", [])
                            if s.get("Resource") != [arn]]
        if kind != "none":
            doc["Statement"].append({
                "Effect": "Allow",
                "Principal": {"AWS": ["*"]},
                "Action": sorted(self._KIND_ACTIONS[kind]),
                "Resource": [arn],
            })
        self.srv.bucket_meta.set_config(
            bucket, "policy",
            json.dumps(doc) if doc["Statement"] else None)
        return {"uiVersion": UI_VERSION}

    def rpc_GetBucketPolicy(self, ak, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        prefix = p.get("prefix", "")
        _allowed(self.srv, ak, "s3:GetBucketPolicy", bucket)
        self.srv.layer.get_bucket_info(bucket)
        arn = self._prefix_arn(bucket, prefix)
        kind = "none"
        for s in self._policy_doc(bucket).get("Statement", []):
            if s.get("Resource") == [arn]:
                kind = self._kind_of(s)
        return {"policy": kind, "uiVersion": UI_VERSION}

    def rpc_ListAllBucketPolicies(self, ak, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        _allowed(self.srv, ak, "s3:GetBucketPolicy", bucket)
        self.srv.layer.get_bucket_info(bucket)
        out = []
        want = f"arn:aws:s3:::{bucket}/"
        for s in self._policy_doc(bucket).get("Statement", []):
            for res in s.get("Resource") or []:
                if res.startswith(want) and res.endswith("*"):
                    out.append({
                        "bucket": bucket,
                        "prefix": res[len(want):-1],
                        "policy": self._kind_of(s)})
        return {"policies": out, "uiVersion": UI_VERSION}

    # -- credentials -------------------------------------------------------

    def rpc_GetAuth(self, ak, _p) -> dict:
        return {"accessKey": ak,
                "secretKey": self.srv.iam.lookup_secret(ak),
                "uiVersion": UI_VERSION}

    def rpc_GenerateAuth(self, ak, _p) -> dict:
        import secrets as pysecrets
        return {"accessKey": pysecrets.token_hex(10).upper(),
                "secretKey": pysecrets.token_urlsafe(30)[:40],
                "uiVersion": UI_VERSION}


def _iso(ns: int) -> str:
    if not ns:
        return ""
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ns / 1e9))


# ---------------------------------------------------------------------------
# HTTP glue — called from the server's dispatch before SigV4 auth
# ---------------------------------------------------------------------------

def _serve_browser(h) -> None:
    import os
    page = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "browser.html")
    with open(page, "rb") as f:
        body = f.read()
    h.send_response(200)
    h.send_header("Content-Type", "text/html; charset=utf-8")
    h.send_header("Content-Length", str(len(body)))
    # the SPA is self-contained; never let a stale cache survive upgrades
    h.send_header("Cache-Control", "no-cache")
    h.end_headers()
    h.wfile.write(body)


def handle(h, srv, path: str, query: dict, read_body) -> bool:
    """Route web endpoints; True when handled.  `read_body` is a thunk so
    the RPC path can bound the read while uploads stream."""
    if path in (BROWSER_PATH, BROWSER_PATH + "/") and h.command == "GET":
        _serve_browser(h)
        return True
    if path == WEBRPC_PATH and h.command == "POST":
        _handle_rpc(h, srv, read_body())
        return True
    if path.startswith(UPLOAD_PREFIX) and h.command == "PUT":
        _handle_upload(h, srv, path, read_body())
        return True
    if path.startswith(DOWNLOAD_PREFIX) and h.command in ("GET", "HEAD"):
        _handle_download(h, srv, path, query)
        return True
    if path == ZIP_PATH and h.command == "POST":
        _handle_zip(h, srv, query, read_body())
        return True
    return False


def _reply_json(h, status: int, doc: dict) -> None:
    body = json.dumps(doc).encode()
    h.send_response(status)
    h.send_header("Content-Type", "application/json")
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    h.wfile.write(body)


def _handle_rpc(h, srv, payload: bytes) -> None:
    if not hasattr(srv, "_webrpc"):
        srv._webrpc = WebRPC(srv)
    try:
        req = json.loads(payload or b"{}")
    except (json.JSONDecodeError, UnicodeDecodeError):
        # invalid UTF-8 raises UnicodeDecodeError, not JSONDecodeError
        return _reply_json(h, 400, {"jsonrpc": "2.0", "id": None,
                                    "error": {"code": -32700,
                                              "message": "parse error"}})
    if not isinstance(req, dict):
        return _reply_json(h, 400, {"jsonrpc": "2.0", "id": None,
                                    "error": {"code": -32600,
                                              "message": "invalid request"}})
    rid = req.get("id")
    method = req.get("method", "")
    params = req.get("params") or {}
    if not isinstance(method, str) or not isinstance(params, dict):
        return _reply_json(h, 400, {"jsonrpc": "2.0", "id": rid,
                                    "error": {"code": -32600,
                                              "message":
                                              "invalid request"}})
    token = ""
    auth = h.headers.get("Authorization", "")
    if auth.startswith("Bearer "):
        token = auth[len("Bearer "):]
    try:
        result = srv._webrpc.dispatch(method, params, token)
        _reply_json(h, 200, {"jsonrpc": "2.0", "id": rid, "result": result})
    except WebError as e:
        _reply_json(h, 401 if isinstance(e, AuthError) else 200,
                    {"jsonrpc": "2.0", "id": rid,
                     "error": {"code": e.code, "message": str(e)}})
    except oli.ObjectLayerError as e:
        _reply_json(h, 200, {"jsonrpc": "2.0", "id": rid,
                             "error": {"code": -32000,
                                       "message": f"{type(e).__name__}: "
                                                  f"{e}"}})
    except Exception as e:  # noqa: BLE001 — malformed params must come
        # back as a JSON-RPC error, never a 500 (go's web handlers
        # return ErrInvalidRequest the same way).  LAST: the narrower
        # handlers above must keep their error codes.
        _reply_json(h, 200, {"jsonrpc": "2.0", "id": rid,
                             "error": {"code": -32603,
                                       "message":
                                       f"internal error: {e}"}})


def _token_of(h, query: dict) -> str:
    auth = h.headers.get("Authorization", "")
    if auth.startswith("Bearer "):
        return auth[len("Bearer "):]
    return query.get("token", [""])[0]


def _handle_upload(h, srv, path: str, payload: bytes) -> None:
    rest = path[len(UPLOAD_PREFIX):]
    bucket, _, key = rest.partition("/")
    try:
        ak = _verify(srv, _token_of(h, {}))
        _allowed(srv, ak, "s3:PutObject", bucket, key)
        opts = oli.PutObjectOptions(user_defined={
            "content-type": h.headers.get("Content-Type",
                                          "application/octet-stream")})
        srv.layer.put_object(bucket, key, payload, opts)
        _reply_json(h, 200, {"ok": True})
    except (WebError, oli.ObjectLayerError) as e:
        _reply_json(h, 401 if isinstance(e, AuthError) else 400,
                    {"ok": False, "error": str(e)})


def _handle_download(h, srv, path: str, query: dict) -> None:
    rest = path[len(DOWNLOAD_PREFIX):]
    bucket, _, key = rest.partition("/")
    try:
        ak = _verify(srv, _token_of(h, query))
        _allowed(srv, ak, "s3:GetObject", bucket, key)
        if h.command == "HEAD":
            # preview probes content type/size without pulling bytes
            info = srv.layer.get_object_info(bucket, key)
            h.send_response(200)
            h.send_header("Content-Type",
                          info.content_type or "application/octet-stream")
            h.send_header("Content-Length", str(info.size))
            h.send_header("Accept-Ranges", "bytes")
            h.end_headers()
            return
        status = 200
        rng = h.headers.get("Range", "")
        m = re.fullmatch(r"bytes=(\d+)-(\d*)", rng.strip()) if rng \
            else None
        if m and m.group(2) and int(m.group(2)) < int(m.group(1)):
            # an EXPLICIT last < first is a syntactically invalid
            # range — RFC 9110 §14.1.1 says ignore the header entirely
            # (an open-ended 'bytes=N-' stays subject to the
            # satisfiability check below)
            m = None
        if m:
            # ranged read through the LAYER (offset/length), not a
            # full materialize-then-slice: preview of a multi-GiB
            # object must read only the requested window
            total = srv.layer.get_object_info(bucket, key).size
            lo = int(m.group(1))
            hi = min(int(m.group(2)) if m.group(2) else total - 1,
                     total - 1)
            if lo >= total:
                # valid but unsatisfiable: 416 + the total the client
                # needs to re-range (RFC 9110 §14.4), never a silent
                # 200 with the whole object
                h.send_response(416)
                h.send_header("Content-Range", f"bytes */{total}")
                h.send_header("Content-Length", "0")
                h.end_headers()
                return
            info, data = srv.layer.get_object(
                bucket, key, offset=lo, length=hi - lo + 1)
            status = 206
            body_gen = None
            entity = len(data)
        else:
            # full download streams chunk-by-chunk through the layer
            # reader — a browser pulling a multi-GiB object costs
            # O(batch), never a whole-object buffer
            info, body_gen = srv.layer.get_object_reader(bucket, key)
            data = b""
            total = entity = info.size
        # header values must never carry CR/LF/quotes from an attacker-
        # chosen object key (response-splitting via percent-encoded keys)
        fname = "".join(c for c in key.rpartition("/")[2]
                        if c.isprintable() and c not in '"\\;')
        h.send_response(status)
        h.send_header("Content-Type",
                      info.content_type or "application/octet-stream")
        h.send_header("Content-Length", str(entity))
        if status == 206:
            h.send_header("Content-Range",
                          f"bytes {lo}-{hi}/{total}")
        h.send_header("Content-Disposition",
                      f'attachment; filename="{fname or "download"}"')
        h.end_headers()
        if body_gen is not None:
            try:
                for chunk in body_gen:
                    if chunk:
                        h.wfile.write(chunk)
            except Exception:  # noqa: BLE001 — headers committed; the
                # short body vs Content-Length signals truncation
                h.close_connection = True
        else:
            h.wfile.write(data)
    except (WebError, oli.ObjectLayerError) as e:
        status = 401 if isinstance(e, AuthError) else 404
        if h.command == "HEAD":
            # RFC 9110: no body on HEAD responses — a JSON error body
            # would desync the HTTP/1.1 keep-alive connection
            h.send_response(status)
            h.send_header("Content-Length", "0")
            h.end_headers()
        else:
            _reply_json(h, status, {"ok": False, "error": str(e)})


class _CountingWriter:
    """Unseekable sink for zipfile: write + tell only, so the archive
    streams to the socket instead of building in memory."""

    def __init__(self, raw):
        self._raw = raw
        self._pos = 0

    def write(self, data):
        self._raw.write(data)
        self._pos += len(data)
        return len(data)

    def tell(self):
        return self._pos

    def flush(self):
        self._raw.flush()


def _handle_zip(h, srv, query: dict, payload: bytes) -> None:
    """DownloadZip (web-handlers.go DownloadZipHandler): stream the
    requested objects/prefixes as one zip archive — one object resident
    at a time, archive bytes written straight to the socket."""
    headers_sent = False
    try:
        ak = _verify(srv, _token_of(h, query))
        req = json.loads(payload or b"{}")
        bucket = req.get("bucketName", "")
        prefix = req.get("prefix", "")
        names: list[str] = []
        for obj in req.get("objects", []):
            full = prefix + obj
            if full.endswith("/"):
                # prefix expansion is a listing; require ListBucket
                _allowed(srv, ak, "s3:ListBucket", bucket)
                res = srv.layer.list_objects(bucket, prefix=full,
                                             max_keys=10 ** 6)
                names += [o.name for o in res.objects]
            else:
                names.append(full)
        for name in names:                  # authorize all before byte 1
            _allowed(srv, ak, "s3:GetObject", bucket, name)
        h.send_response(200)
        h.send_header("Content-Type", "application/zip")
        # length unknown up front: delimit by closing the connection
        h.send_header("Connection", "close")
        h.end_headers()
        headers_sent = True
        with zipfile.ZipFile(_CountingWriter(h.wfile), "w",
                             zipfile.ZIP_DEFLATED) as zf:
            for name in names:
                # stream each member through the layer reader into the
                # archive — one CHUNK resident at a time, so zipping a
                # prefix of multi-GiB objects stays O(batch)
                _, body = srv.layer.get_object_reader(bucket, name)
                zi = zipfile.ZipInfo(name[len(prefix):] or name,
                                     date_time=time.localtime()[:6])
                zi.compress_type = zipfile.ZIP_DEFLATED
                with zf.open(zi, "w") as zb:
                    for chunk in body:
                        zb.write(chunk)
        h.close_connection = True
    except (WebError, oli.ObjectLayerError) as e:
        if headers_sent:
            # zip bytes already on the wire: a JSON reply here would
            # corrupt the stream — just drop the connection, the
            # Connection: close delimiting signals truncation
            h.close_connection = True
            return
        _reply_json(h, 401 if isinstance(e, AuthError) else 400,
                    {"ok": False, "error": str(e)})
