"""Minimal S3 client (SigV4) — used by the test suite, the replication
worker, and as the `mc`-style round-trip tool (the reference tests against
minio-go/mc; we carry our own client since the image has no boto3).
"""

from __future__ import annotations

import http.client
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass

from .sigv4 import Credentials, presign_url, sign_request

S3_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


class S3ClientError(Exception):
    def __init__(self, status: int, code: str, message: str = ""):
        super().__init__(f"{status} {code}: {message}")
        self.status = status
        self.code = code


@dataclass
class S3Response:
    status: int
    headers: dict[str, str]
    body: bytes

    def xml(self) -> ET.Element:
        return ET.fromstring(self.body)


@dataclass
class S3Client:
    endpoint: str                       # http(s)://host:port
    access_key: str
    secret_key: str
    region: str = "us-east-1"
    # https endpoints: CA bundle pinning the server (a deployment CA,
    # not the public web's).  When unset, the process-global
    # secure.transport registry answers (a cluster that armed TLS
    # already pinned its CA there), else the system trust store.
    ca_file: str | None = None

    @property
    def _creds(self) -> Credentials:
        return Credentials(self.access_key, self.secret_key)

    def _connect(self, u) -> http.client.HTTPConnection:
        if u.scheme == "https":
            from ..secure import transport as _tls_transport
            ctx = None
            if self.ca_file:
                # built once per client (a CA bundle parse per REQUEST
                # would tax every soak worker), invalidated never —
                # the pin is immutable for the client's lifetime
                ctx = getattr(self, "_ctx_cache", None)
                if ctx is None:
                    import ssl
                    ctx = ssl.create_default_context(cafile=self.ca_file)
                    self._ctx_cache = ctx
            return _tls_transport.https_connection(
                u.hostname, u.port, 60, plane="s3", context=ctx)
        return http.client.HTTPConnection(u.hostname, u.port, timeout=60)

    def request(self, method: str, path: str, query: str = "",
                body: bytes = b"", headers: dict | None = None,
                sign: bool = True, expect=(200, 204, 206)) -> S3Response:
        path = urllib.parse.quote(path, safe="/~-._")  # keys may have spaces
        url = self.endpoint + path + (f"?{query}" if query else "")
        hdrs = dict(headers or {})
        if sign:
            hdrs = sign_request(self._creds, method, url, hdrs, body,
                                self.region)
        u = urllib.parse.urlsplit(url)
        conn = self._connect(u)
        try:
            conn.request(method, u.path + (f"?{u.query}" if u.query else ""),
                         body=body, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            out = S3Response(resp.status, dict(resp.getheaders()), data)
        finally:
            conn.close()
        if expect and out.status not in expect:
            code, msg = "Unknown", ""
            try:
                e = out.xml()
                code = e.findtext("Code") or code
                msg = e.findtext("Message") or ""
            except ET.ParseError:
                pass
            raise S3ClientError(out.status, code, msg)
        return out

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        self.request("PUT", f"/{bucket}")

    def delete_bucket(self, bucket: str) -> None:
        self.request("DELETE", f"/{bucket}")

    def head_bucket(self, bucket: str) -> bool:
        try:
            self.request("HEAD", f"/{bucket}")
            return True
        except S3ClientError:
            return False

    def list_buckets(self) -> list[str]:
        r = self.request("GET", "/")
        return [b.findtext(f"{S3_NS}Name")
                for b in r.xml().iter(f"{S3_NS}Bucket")]

    def set_versioning(self, bucket: str, enabled: bool = True) -> None:
        status = "Enabled" if enabled else "Suspended"
        body = (f'<VersioningConfiguration xmlns='
                f'"http://s3.amazonaws.com/doc/2006-03-01/">'
                f"<Status>{status}</Status>"
                f"</VersioningConfiguration>").encode()
        self.request("PUT", f"/{bucket}", "versioning", body)

    # -- objects -----------------------------------------------------------

    def put_object(self, bucket: str, key: str, data: bytes,
                   content_type: str | None = None,
                   metadata: dict | None = None) -> S3Response:
        hdrs = {}
        if content_type:
            hdrs["Content-Type"] = content_type
        for k, v in (metadata or {}).items():
            hdrs[f"x-amz-meta-{k}"] = v
        return self.request("PUT", f"/{bucket}/{key}", body=data,
                            headers=hdrs)

    def get_object(self, bucket: str, key: str,
                   version_id: str | None = None,
                   byte_range: tuple[int, int] | None = None,
                   range_header: str | None = None) -> S3Response:
        q = f"versionId={version_id}" if version_id else ""
        hdrs = {}
        if range_header:
            hdrs["Range"] = range_header
        elif byte_range:
            hdrs["Range"] = f"bytes={byte_range[0]}-{byte_range[1]}"
        return self.request("GET", f"/{bucket}/{key}", q, headers=hdrs)

    def head_object(self, bucket: str, key: str,
                    version_id: str | None = None) -> S3Response:
        q = f"versionId={version_id}" if version_id else ""
        return self.request("HEAD", f"/{bucket}/{key}", q)

    def delete_object(self, bucket: str, key: str,
                      version_id: str | None = None) -> S3Response:
        q = f"versionId={version_id}" if version_id else ""
        return self.request("DELETE", f"/{bucket}/{key}", q)

    def list_objects(self, bucket: str, prefix: str = "",
                     delimiter: str = "", v2: bool = True,
                     marker: str = "", max_keys: int = 0
                     ) -> tuple[list[dict], list[str]]:
        page = self.list_objects_page(bucket, prefix, delimiter, v2,
                                      marker, max_keys)
        return page["objects"], page["prefixes"]

    def list_objects_page(self, bucket: str, prefix: str = "",
                          delimiter: str = "", v2: bool = True,
                          marker: str = "", max_keys: int = 0) -> dict:
        """One remote listing page with continuation state (the shape the
        S3 gateway needs to forward pagination faithfully)."""
        q = []
        if v2:
            q.append("list-type=2")
            if marker:
                q.append("continuation-token="
                         f"{urllib.parse.quote(marker)}")
        elif marker:
            q.append(f"marker={urllib.parse.quote(marker)}")
        if prefix:
            q.append(f"prefix={urllib.parse.quote(prefix)}")
        if delimiter:
            q.append(f"delimiter={urllib.parse.quote(delimiter)}")
        if max_keys:
            q.append(f"max-keys={max_keys}")
        r = self.request("GET", f"/{bucket}", "&".join(q))
        root = r.xml()
        objs = [{
            "key": c.findtext(f"{S3_NS}Key"),
            "size": int(c.findtext(f"{S3_NS}Size")),
            "etag": (c.findtext(f"{S3_NS}ETag") or "").strip('"'),
            "last_modified": c.findtext(f"{S3_NS}LastModified") or "",
        } for c in root.iter(f"{S3_NS}Contents")]
        prefixes = [p.findtext(f"{S3_NS}Prefix")
                    for p in root.iter(f"{S3_NS}CommonPrefixes")]
        truncated = (root.findtext(f"{S3_NS}IsTruncated") or "") == "true"
        next_marker = (root.findtext(f"{S3_NS}NextContinuationToken") or
                       root.findtext(f"{S3_NS}NextMarker") or "")
        if truncated and not next_marker and objs:
            # V1 without a delimiter omits NextMarker: last key continues
            next_marker = objs[-1]["key"]
        return {
            "objects": objs, "prefixes": prefixes,
            "is_truncated": truncated,
            "next_marker": next_marker,
        }

    def list_object_versions(self, bucket: str, prefix: str = "") -> ET.Element:
        q = "versions" + (f"&prefix={urllib.parse.quote(prefix)}"
                          if prefix else "")
        return self.request("GET", f"/{bucket}", q).xml()

    def delete_objects(self, bucket: str, keys: list[str]) -> ET.Element:
        parts = "".join(f"<Object><Key>{k}</Key></Object>" for k in keys)
        body = (f'<Delete xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                f"{parts}</Delete>").encode()
        return self.request("POST", f"/{bucket}", "delete", body).xml()

    def presign(self, method: str, bucket: str, key: str,
                expires: int = 3600) -> str:
        return presign_url(self._creds, method,
                           f"{self.endpoint}/{bucket}/{key}", expires,
                           self.region)

    # -- multipart (used by the S3 gateway passthrough) ---------------------

    def create_multipart_upload(self, bucket: str, key: str,
                                headers: dict | None = None) -> str:
        r = self.request("POST", f"/{bucket}/{key}", "uploads",
                         headers=headers)
        root = r.xml()
        return root.findtext(f"{S3_NS}UploadId") or \
            root.findtext("UploadId") or ""

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_number: int, data: bytes) -> str:
        r = self.request(
            "PUT", f"/{bucket}/{key}",
            f"partNumber={part_number}&uploadId={upload_id}", body=data)
        hdrs = {k.lower(): v for k, v in r.headers.items()}
        return hdrs.get("etag", "").strip('"')

    def complete_multipart_upload(self, bucket: str, key: str,
                                  upload_id: str,
                                  parts: list[tuple[int, str]]) -> ET.Element:
        body = "".join(
            f"<Part><PartNumber>{n}</PartNumber><ETag>{etag}</ETag></Part>"
            for n, etag in parts)
        xml = (f'<CompleteMultipartUpload xmlns='
               f'"http://s3.amazonaws.com/doc/2006-03-01/">{body}'
               f"</CompleteMultipartUpload>").encode()
        return self.request("POST", f"/{bucket}/{key}",
                            f"uploadId={upload_id}", xml).xml()

    def abort_multipart_upload(self, bucket: str, key: str,
                               upload_id: str) -> None:
        self.request("DELETE", f"/{bucket}/{key}", f"uploadId={upload_id}")

    def list_parts(self, bucket: str, key: str,
                   upload_id: str) -> list[dict]:
        r = self.request("GET", f"/{bucket}/{key}", f"uploadId={upload_id}")
        return [{
            "part_number": int(p.findtext(f"{S3_NS}PartNumber") or 0),
            "etag": (p.findtext(f"{S3_NS}ETag") or "").strip('"'),
            "size": int(p.findtext(f"{S3_NS}Size") or 0),
        } for p in r.xml().iter(f"{S3_NS}Part")]

    def list_multipart_uploads(self, bucket: str) -> list[dict]:
        r = self.request("GET", f"/{bucket}", "uploads")
        return [{
            "key": u.findtext(f"{S3_NS}Key"),
            "upload_id": u.findtext(f"{S3_NS}UploadId"),
        } for u in r.xml().iter(f"{S3_NS}Upload")]
