"""HighwayHash-256 — bitrot checksum (reference: cmd/bitrot.go:30-57).

The reference's default bitrot algorithm is keyed HighwayHash256 with a fixed
magic key (HH-256 of the first 100 decimals of pi under a zero key,
cmd/bitrot.go:31).  Here:

  * primary path: portable C implementation (native/highwayhash.c) compiled
    on first use and driven via ctypes -- the host-native analog of the
    reference's AVX2 assembly dependency;
  * fallback: pure-Python implementation (slow, used when no compiler).

Both are validated against the published HighwayHash64 test vectors.
"""

from __future__ import annotations

import ctypes
import os
import struct

# cmd/bitrot.go:31 — magic HH-256 key
MAGIC_KEY = (b"\x4b\xe7\x34\xfa\x8e\x23\x8a\xcd\x26\x3e\x83\xe6\xbb\x96\x85"
             b"\x52\x04\x0f\x93\x5d\xa3\x9f\x44\x14\x97\xe0\x9d\x13\x22\xde"
             b"\x36\xa0")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB = None
_LIB_TRIED = False


def _get_lib():
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    from ..utils import nativelib
    src = os.path.join(_NATIVE_DIR, "highwayhash.c")
    so = os.path.join(_NATIVE_DIR, "libmt_hash.so")
    lib = nativelib.load(src, so)
    if lib is not None:
        try:
            lib.mt_hh256.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                     ctypes.c_size_t, ctypes.c_char_p]
            lib.mt_hh64.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                    ctypes.c_size_t]
            lib.mt_hh64.restype = ctypes.c_uint64
            lib.mt_hh256_blocks.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_size_t, ctypes.c_char_p]
            lib.mt_hh256_frame.argtypes = [
                ctypes.c_char_p, ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_size_t, ctypes.c_char_p]
            lib.mt_hh256_fill.argtypes = [
                ctypes.c_char_p, ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_size_t]
            lib.mt_hh256_verify_framed.argtypes = [
                ctypes.c_char_p, ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_size_t]
            lib.mt_hh256_verify_framed.restype = ctypes.c_int
            lib.mt_hh_stream_size.restype = ctypes.c_size_t
            lib.mt_hh_stream_init.argtypes = [ctypes.c_char_p,
                                              ctypes.c_char_p]
            lib.mt_hh_stream_update.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
            lib.mt_hh_stream_final256.argtypes = [ctypes.c_char_p,
                                                  ctypes.c_char_p]
        except Exception:  # noqa: BLE001
            lib = None
    _LIB = lib
    _LIB_TRIED = True
    return _LIB


# ---------------------------------------------------------------------------
# pure-Python fallback (bit-identical, slow)
# ---------------------------------------------------------------------------

_M64 = (1 << 64) - 1
_INIT_MUL0 = (0xDBE6D5D5FE4CCE2F, 0xA4093822299F31D0,
              0x13198A2E03707344, 0x243F6A8885A308D3)
_INIT_MUL1 = (0x3BD39E10CB0EF593, 0xC0ACF169B5F18A8C,
              0xBE5466CF34E90C6C, 0x452821E638D01377)


class _PyState:
    __slots__ = ("v0", "v1", "mul0", "mul1")

    def __init__(self, key: bytes):
        k = struct.unpack("<4Q", key)
        self.mul0 = list(_INIT_MUL0)
        self.mul1 = list(_INIT_MUL1)
        self.v0 = [m ^ kk for m, kk in zip(_INIT_MUL0, k)]
        self.v1 = [m ^ (((kk >> 32) | (kk << 32)) & _M64)
                   for m, kk in zip(_INIT_MUL1, k)]

    def _zipper(self, v1, v0):
        add0 = ((((v0 & 0xFF000000) | (v1 & 0xFF00000000)) >> 24)
                | (((v0 & 0xFF0000000000) | (v1 & 0xFF000000000000)) >> 16)
                | (v0 & 0xFF0000) | ((v0 & 0xFF00) << 32)
                | ((v1 & 0xFF00000000000000) >> 8) | ((v0 << 56) & _M64))
        add1 = ((((v1 & 0xFF000000) | (v0 & 0xFF00000000)) >> 24)
                | (v1 & 0xFF0000) | ((v1 & 0xFF0000000000) >> 16)
                | ((v1 & 0xFF00) << 24) | ((v0 & 0xFF000000000000) >> 8)
                | ((v1 & 0xFF) << 48) | (v0 & 0xFF00000000000000))
        return add1, add0

    def update_lanes(self, lanes):
        v0, v1, mul0, mul1 = self.v0, self.v1, self.mul0, self.mul1
        for i in range(4):
            v1[i] = (v1[i] + mul0[i] + lanes[i]) & _M64
            mul0[i] ^= ((v1[i] & 0xFFFFFFFF) * (v0[i] >> 32)) & _M64
            v0[i] = (v0[i] + mul1[i]) & _M64
            mul1[i] ^= ((v0[i] & 0xFFFFFFFF) * (v1[i] >> 32)) & _M64
        a1, a0 = self._zipper(v1[1], v1[0])
        v0[1] = (v0[1] + a1) & _M64
        v0[0] = (v0[0] + a0) & _M64
        a1, a0 = self._zipper(v1[3], v1[2])
        v0[3] = (v0[3] + a1) & _M64
        v0[2] = (v0[2] + a0) & _M64
        a1, a0 = self._zipper(v0[1], v0[0])
        v1[1] = (v1[1] + a1) & _M64
        v1[0] = (v1[0] + a0) & _M64
        a1, a0 = self._zipper(v0[3], v0[2])
        v1[3] = (v1[3] + a1) & _M64
        v1[2] = (v1[2] + a0) & _M64

    def update_packet(self, packet: bytes):
        self.update_lanes(struct.unpack("<4Q", packet))

    def update_remainder(self, tail: bytes):
        size = len(tail)
        assert 0 < size < 32
        size_mod4 = size & 3
        rem_off = size & ~3
        for i in range(4):
            self.v0[i] = (self.v0[i] + (size << 32) + size) & _M64
        # rotate each 32-bit half of v1 left by size
        for i in range(4):
            h0 = self.v1[i] & 0xFFFFFFFF
            h1 = self.v1[i] >> 32
            h0 = ((h0 << size) | (h0 >> (32 - size))) & 0xFFFFFFFF
            h1 = ((h1 << size) | (h1 >> (32 - size))) & 0xFFFFFFFF
            self.v1[i] = (h1 << 32) | h0
        packet = bytearray(32)
        packet[:rem_off] = tail[:rem_off]
        remainder = tail[rem_off:]
        if size & 16:
            for i in range(4):
                packet[28 + i] = tail[rem_off + i + size_mod4 - 4]
        elif size_mod4:
            packet[16] = remainder[0]
            packet[17] = remainder[size_mod4 >> 1]
            packet[18] = remainder[size_mod4 - 1]
        self.update_packet(bytes(packet))

    def _permute_update(self):
        v = self.v0
        self.update_lanes((
            ((v[2] >> 32) | (v[2] << 32)) & _M64,
            ((v[3] >> 32) | (v[3] << 32)) & _M64,
            ((v[0] >> 32) | (v[0] << 32)) & _M64,
            ((v[1] >> 32) | (v[1] << 32)) & _M64))

    def finalize64(self) -> int:
        for _ in range(4):
            self._permute_update()
        return (self.v0[0] + self.v1[0] + self.mul0[0] + self.mul1[0]) & _M64

    def finalize256(self) -> bytes:
        for _ in range(10):
            self._permute_update()

        def modred(a3u, a2, a1, a0):
            a3 = a3u & 0x3FFFFFFFFFFFFFFF
            m1 = a1 ^ (((a3 << 1) | (a2 >> 63)) & _M64) \
                ^ (((a3 << 2) | (a2 >> 62)) & _M64)
            m0 = a0 ^ ((a2 << 1) & _M64) ^ ((a2 << 2) & _M64)
            return m0, m1

        h0, h1 = modred((self.v1[1] + self.mul1[1]) & _M64,
                        (self.v1[0] + self.mul1[0]) & _M64,
                        (self.v0[1] + self.mul0[1]) & _M64,
                        (self.v0[0] + self.mul0[0]) & _M64)
        h2, h3 = modred((self.v1[3] + self.mul1[3]) & _M64,
                        (self.v1[2] + self.mul1[2]) & _M64,
                        (self.v0[3] + self.mul0[3]) & _M64,
                        (self.v0[2] + self.mul0[2]) & _M64)
        return struct.pack("<4Q", h0, h1, h2, h3)


def _py_process(key: bytes, data: bytes) -> _PyState:
    s = _PyState(key)
    n = len(data)
    i = 0
    while i + 32 <= n:
        s.update_packet(data[i:i + 32])
        i += 32
    if n & 31:
        s.update_remainder(data[i:])
    return s


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

DIGEST_SIZE = 32


def hh256(data, key: bytes = MAGIC_KEY) -> bytes:
    """One-shot HighwayHash-256 (the per-shard-block bitrot checksum)."""
    data = bytes(data)
    lib = _get_lib()
    if lib is not None:
        out = ctypes.create_string_buffer(32)
        lib.mt_hh256(key, data, len(data), out)
        return out.raw
    return _py_process(key, data).finalize256()


def hh64(data, key: bytes = MAGIC_KEY) -> int:
    data = bytes(data)
    lib = _get_lib()
    if lib is not None:
        return int(lib.mt_hh64(key, data, len(data)))
    return _py_process(key, data).finalize64()


def hh256_blocks(data, block_size: int, key: bytes = MAGIC_KEY) -> list[bytes]:
    """Hash consecutive blocks (last may be short): the bitrot verify sweep."""
    data = bytes(data)
    if block_size <= 0:
        raise ValueError("block_size must be positive")
    count = (len(data) + block_size - 1) // block_size
    lib = _get_lib()
    if lib is not None:
        out = ctypes.create_string_buffer(32 * count)
        lib.mt_hh256_blocks(key, data, len(data), block_size, out)
        return [out.raw[i * 32:(i + 1) * 32] for i in range(count)]
    return [hh256(data[i * block_size:(i + 1) * block_size], key)
            for i in range(count)]


def hh256_fill(framed, block_size: int, key: bytes = MAGIC_KEY) -> bool:
    """Fill digest slots of an already-framed [32B hash][block] buffer
    IN PLACE (one GIL-free native pass over a writable numpy row /
    memoryview).  The zero-copy PUT pipeline lays shard bytes straight
    into frame payloads and then calls this.  Returns False when the
    native library is unavailable (caller falls back to hh256_frame)."""
    if block_size <= 0:
        raise ValueError("block_size must be positive")
    lib = _get_lib()
    if lib is None:
        return False
    import numpy as np
    arr = np.frombuffer(framed, dtype=np.uint8) \
        if not isinstance(framed, np.ndarray) else framed
    if not (arr.flags["C_CONTIGUOUS"] and arr.flags["WRITEABLE"]):
        raise ValueError("hh256_fill needs a writable contiguous buffer")
    lib.mt_hh256_fill(key, arr.ctypes.data_as(ctypes.c_void_p),
                      arr.size, block_size)
    return True


def hh256_verify_framed(framed, block_size: int,
                        key: bytes = MAGIC_KEY) -> int | None:
    """Verify every block digest of a framed [32B hash][block] buffer
    in ONE GIL-free native pass (the GET-side dual of hh256_fill).

    Returns 0 when all blocks verify, the 1-based index of the first
    corrupt block otherwise, or None when the native library is
    unavailable (caller falls back to the per-block Python reader)."""
    if block_size <= 0:
        raise ValueError("block_size must be positive")
    lib = _get_lib()
    if lib is None:
        return None
    import numpy as np
    arr = np.frombuffer(framed, dtype=np.uint8) \
        if not isinstance(framed, np.ndarray) else framed
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return int(lib.mt_hh256_verify_framed(
        key, arr.ctypes.data_as(ctypes.c_void_p), arr.size, block_size))


def hh256_frame(data, block_size: int, key: bytes = MAGIC_KEY) -> bytes:
    """Frame a shard file (hash || block per block) in ONE native pass.

    The bitrot writer's hot path (cmd/bitrot-streaming.go:46-58): hash
    and interleave happen inside a single GIL-releasing C call, so
    concurrent PUTs scale.  Accepts any contiguous buffer (bytes,
    numpy, memoryview) without copying on the native path."""
    if block_size <= 0:
        raise ValueError("block_size must be positive")
    mv = memoryview(data).cast("B")
    size = len(mv)
    if size == 0:
        return b""
    count = (size + block_size - 1) // block_size
    lib = _get_lib()
    if lib is not None:
        import numpy as np
        arr = np.frombuffer(mv, dtype=np.uint8)     # zero-copy view
        out = ctypes.create_string_buffer(size + 32 * count)
        lib.mt_hh256_frame(key, arr.ctypes.data_as(ctypes.c_void_p),
                           size, block_size, out)
        return out.raw
    # pure-python fallback: identical framing
    b = mv.tobytes()
    parts = []
    for i in range(count):
        blk = b[i * block_size:(i + 1) * block_size]
        parts.append(hh256(blk, key))
        parts.append(blk)
    return b"".join(parts)


class HighwayHash256:
    """Streaming hash.Hash-style interface (whole-file bitrot writer)."""

    digest_size = DIGEST_SIZE
    name = "highwayhash256"

    def __init__(self, key: bytes = MAGIC_KEY):
        self._key = key
        self._lib = _get_lib()
        if self._lib is not None:
            self._st = ctypes.create_string_buffer(
                self._lib.mt_hh_stream_size())
            self._lib.mt_hh_stream_init(self._st, key)
        else:
            self._buf = bytearray()

    def update(self, data) -> None:
        data = bytes(data)
        if self._lib is not None:
            self._lib.mt_hh_stream_update(self._st, data, len(data))
        else:
            self._buf += data

    def digest(self) -> bytes:
        if self._lib is not None:
            # finalize a copy so the stream stays usable
            st_copy = ctypes.create_string_buffer(self._st.raw)
            out = ctypes.create_string_buffer(32)
            self._lib.mt_hh_stream_final256(st_copy, out)
            return out.raw
        return _py_process(self._key, bytes(self._buf)).finalize256()

    def hexdigest(self) -> str:
        return self.digest().hex()

    def reset(self) -> None:
        if self._lib is not None:
            self._lib.mt_hh_stream_init(self._st, self._key)
        else:
            self._buf = bytearray()
