"""Device-side multi-buffer MD5 — the strict-compat ETag off the host
entirely (ISSUE 12 tentpole b).

MD5 is an irreducible serial chain per stream, but the chain step is
64 rounds of u32 add/rotate/boolean — and ``native/md5mb.cc`` already
showed the multi-buffer trick: advance N INDEPENDENT digests in
lock-step, message schedule stored word-major so every round's loads
are contiguous across lanes.  That is a batch axis, and a batch axis
is what the device is for (the same reshape that turned GF(2^8) into
matmuls, ops/gf8.py): states become an (N, 4) u32 array, one 64-byte
block becomes an (N, 16) u32 slice, and the whole block loop runs as
ONE device dispatch under ``lax.fori_loop`` — concurrent strict-ETag
streams coalesce into one launch instead of taxing host cores.

Layering (mirrors hashing/md5fast.py):

  * ``advance(states, words, nblocks)`` — the batched compress: each
    lane advances by its OWN block count (ragged batches mask with
    ``t < nblocks``), shapes bucketed to powers of two so the jit
    cache stays small;
  * ``MD5Device`` — a hashlib-compatible digest object: whole 64-byte
    blocks ride the device (through the ``md5`` combining bucket in
    parallel/batcher.py), sub-block tails and the final padding run a
    host scalar compress (≤2 blocks per digest — microseconds);
  * ``available()`` / ``unavailable_reason()`` — the degradation
    contract: no usable jax device (or import failure) yields a NAMED
    reason, and hashing/md5fast.py drops to the host lane scheduler —
    the fallback ladder is device → native lanes → hashlib;
  * ``device_rate_gibps()`` — the auto-backend calibration probe: a
    host-behind-a-slow-tunnel TPU loses to the native host core, so
    ``pipeline.md5_backend=auto`` MEASURES both once and picks the
    winner instead of trusting the platform name.

Digests are bit-identical to RFC 1321 / hashlib for every lane count,
length and update split (tests/test_fused_kernel.py pins the md5fast
boundary lengths 0/1/55/56/63/64/65/4MiB±1 and split updates).
"""

from __future__ import annotations

import struct
import time

import numpy as np

# RFC 1321 tables (identical to native/md5mb.cc)
_K = [
    0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee,
    0xf57c0faf, 0x4787c62a, 0xa8304613, 0xfd469501,
    0x698098d8, 0x8b44f7af, 0xffff5bb1, 0x895cd7be,
    0x6b901122, 0xfd987193, 0xa679438e, 0x49b40821,
    0xf61e2562, 0xc040b340, 0x265e5a51, 0xe9b6c7aa,
    0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8,
    0x21e1cde6, 0xc33707d6, 0xf4d50d87, 0x455a14ed,
    0xa9e3e905, 0xfcefa3f8, 0x676f02d9, 0x8d2a4c8a,
    0xfffa3942, 0x8771f681, 0x6d9d6122, 0xfde5380c,
    0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70,
    0x289b7ec6, 0xeaa127fa, 0xd4ef3085, 0x04881d05,
    0xd9d4d039, 0xe6db99e5, 0x1fa27cf8, 0xc4ac5665,
    0xf4292244, 0x432aff97, 0xab9423a7, 0xfc93a039,
    0x655b59c3, 0x8f0ccc92, 0xffeff47d, 0x85845dd1,
    0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
    0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391,
]
_S = [
    7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
    5, 9, 14, 20, 5, 9, 14, 20, 5, 9, 14, 20, 5, 9, 14, 20,
    4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
    6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21,
]
_INIT = (0x67452301, 0xefcdab89, 0x98badcfe, 0x10325476)


def _msg_index(i: int) -> int:
    if i < 16:
        return i
    if i < 32:
        return (5 * i + 1) % 16
    if i < 48:
        return (3 * i + 5) % 16
    return (7 * i) % 16


# -- availability -----------------------------------------------------------

_AVAIL: bool | None = None
_REASON = ""


def available() -> bool:
    """True when a jax device can run the batched compress.  The CPU
    backend COUNTS as a device (tests and virtual meshes exercise the
    exact production code path); whether it is WORTH using is the auto
    calibration's call, not this one's."""
    global _AVAIL, _REASON
    if _AVAIL is not None:
        return _AVAIL
    try:
        import jax
        devs = jax.devices()
        if not devs:
            raise RuntimeError("jax reports zero devices")
        _AVAIL, _REASON = True, ""
    except Exception as e:  # noqa: BLE001 — the reason IS the contract
        _AVAIL = False
        _REASON = f"device MD5 unavailable: {type(e).__name__}: {e}"
    return _AVAIL


def unavailable_reason() -> str:
    """The named degradation reason (test skip messages + the
    mt_md5_device_fallback_total increment site quote this)."""
    available()
    return _REASON


def _reset_for_tests() -> None:
    global _AVAIL, _REASON, _RATE
    _AVAIL, _REASON, _RATE = None, "", None


# -- the batched compress ---------------------------------------------------


def _advance_fn():
    """Build (once) the jitted batched compress.  Shapes recompile per
    (N_pad, nb_pad) bucket; both are padded to powers of two by
    ``advance`` so the cache stays at a handful of entries."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def adv(h, words, nblocks):
        # h: (N, 4) u32; words: (N, nb, 16) u32 little-endian message
        # words; nblocks: (N,) i32 — lane l advances by nblocks[l]
        # blocks, further blocks are masked no-ops (ragged batches).
        def body(t, h):
            a = h[:, 0]
            b = h[:, 1]
            c = h[:, 2]
            d = h[:, 3]
            m = words[:, t]                      # (N, 16) word-major
            for i in range(64):
                if i < 16:
                    f = (b & c) | (~b & d)
                elif i < 32:
                    f = (d & b) | (~d & c)
                elif i < 48:
                    f = b ^ c ^ d
                else:
                    f = c ^ (b | ~d)
                f = f + a + jnp.uint32(_K[i]) + m[:, _msg_index(i)]
                a, d, c = d, c, b
                s = _S[i]
                b = b + ((f << s) | (f >> (32 - s)))
            h2 = jnp.stack([h[:, 0] + a, h[:, 1] + b,
                            h[:, 2] + c, h[:, 3] + d], axis=1)
            mask = (t < nblocks)[:, None]
            return jnp.where(mask, h2, h)

        return jax.lax.fori_loop(0, words.shape[1], body, h)

    return adv


_ADV = None


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def advance(states: np.ndarray, words: np.ndarray,
            nblocks: np.ndarray) -> np.ndarray:
    """Advance N digests by their own block counts in ONE dispatch.

    states: (N, 4) u32; words: (N, nb, 16) u32 (lane l's blocks beyond
    nblocks[l] may be garbage — they are masked); nblocks: (N,) ints.
    Returns the new (N, 4) u32 states (host numpy).
    """
    global _ADV
    if _ADV is None:
        _ADV = _advance_fn()
    import jax.numpy as jnp
    N, nb = words.shape[0], words.shape[1]
    np_, nbp = _pow2(max(1, N)), _pow2(max(1, nb))
    if np_ != N or nbp != nb:
        w = np.zeros((np_, nbp, 16), dtype=np.uint32)
        w[:N, :nb] = words
        st = np.zeros((np_, 4), dtype=np.uint32)
        st[:N] = states
        nv = np.zeros((np_,), dtype=np.int32)
        nv[:N] = nblocks
    else:
        w, st = words, np.asarray(states, np.uint32)
        nv = np.asarray(nblocks, np.int32)
    out = _ADV(jnp.asarray(st), jnp.asarray(w), jnp.asarray(nv))
    return np.asarray(out)[:N]


# -- host scalar compress (tails + finalization only) -----------------------


def _compress_host(h: list[int], block: bytes) -> list[int]:
    """One-block RFC 1321 compress in pure Python — only sub-block
    tails and the final padding ride this (≤2 blocks per digest)."""
    M = 0xFFFFFFFF
    m = struct.unpack("<16I", block)
    a, b, c, d = h
    for i in range(64):
        if i < 16:
            f = (b & c) | (~b & d & M)
        elif i < 32:
            f = (d & b) | (~d & c & M)
        elif i < 48:
            f = b ^ c ^ d
        else:
            f = c ^ ((b | (~d & M)))
        f = (f + a + _K[i] + m[_msg_index(i)]) & M
        a, d, c = d, c, b
        s = _S[i]
        b = (b + (((f << s) | (f >> (32 - s))) & M)) & M
    return [(h[0] + a) & M, (h[1] + b) & M, (h[2] + c) & M,
            (h[3] + d) & M]


class MD5Device:
    """hashlib.md5-compatible digest whose bulk blocks run on the
    device.  Whole 64-byte blocks route through the ``md5`` combining
    bucket (parallel/batcher.py) so concurrent streams coalesce into
    one dispatch; the sub-block tail and final padding run the host
    scalar compress.  ``digest`` finalizes a copy, so the stream stays
    usable (the stdlib contract)."""

    name = "md5"
    digest_size = 16
    block_size = 64

    __slots__ = ("_h", "_n", "_tail", "_dispatch")

    def __init__(self, data=b"", dispatch=None):
        self._h = list(_INIT)
        self._n = 0
        self._tail = b""
        # dispatch(h4_u32, words (nb, 16) u32) -> new h4_u32; defaults
        # to the md5 combining bucket (late import: batcher pulls the
        # codec plane in, and hashing must stay importable without it)
        self._dispatch = dispatch
        if data:
            self.update(data)

    # blocks per bucket submission: 1 MiB — the md5fast.ONESHOT_SLICE
    # discipline.  A whole 64 MiB stream-batch chunk submitted as one
    # advance would overflow the bucket's queue bound and shed every
    # time (never coalescing — the measured PR-6 failure mode of
    # whole-buffer oneshots, one level down); slab-sized submissions
    # interleave concurrent streams across batched dispatches.
    _SLAB_BLOCKS = (1 << 20) // 64

    def _advance_blocks(self, words: np.ndarray) -> None:
        if self._dispatch is None:
            from ..parallel import batcher
            self._dispatch = batcher.MD5_GLOBAL.advance
        for off in range(0, words.shape[0], self._SLAB_BLOCKS):
            self._h = list(int(x) for x in self._dispatch(
                np.asarray(self._h, np.uint32),
                words[off:off + self._SLAB_BLOCKS]))

    def update(self, data) -> None:
        mv = memoryview(data)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        n = len(mv)
        if n == 0:
            return
        self._n += n
        if self._tail:
            take = min(64 - len(self._tail), n)
            self._tail += bytes(mv[:take])
            mv = mv[take:]
            n -= take
            if len(self._tail) == 64:
                self._h = _compress_host(self._h, self._tail)
                self._tail = b""
            if n == 0:
                return
        nb = n // 64
        if nb:
            words = np.frombuffer(mv[:nb * 64], dtype="<u4") \
                .reshape(nb, 16)
            self._advance_blocks(words)
        if n % 64:
            self._tail = bytes(mv[nb * 64:])

    def digest(self) -> bytes:
        h = list(self._h)
        bits = self._n * 8
        pad = self._tail + b"\x80" + b"\x00" * (
            (119 - len(self._tail)) % 64) + struct.pack("<Q", bits)
        for off in range(0, len(pad), 64):
            h = _compress_host(h, pad[off:off + 64])
        return struct.pack("<4I", *h)

    def hexdigest(self) -> str:
        return self.digest().hex()

    def copy(self) -> "MD5Device":
        c = MD5Device.__new__(MD5Device)
        c._h = list(self._h)
        c._n = self._n
        c._tail = self._tail
        c._dispatch = self._dispatch
        return c


# -- auto-backend calibration ----------------------------------------------

_RATE: float | None = None


def device_rate_gibps(slices: int = 4,
                      kib_per_slice: int = 1024) -> float:
    """Measured end-to-end device MD5 rate through the PRODUCTION
    path: an ``MD5Device`` updated slice by slice through the ``md5``
    combining bucket, so the probe pays everything a real strict-ETag
    stream pays — the host->device transfer of the schedule words (the
    dominant cost on a tunnel-attached device) AND the bucket's
    combining-window wait per slice.  The slice size matches
    ``md5fast.ONESHOT_SLICE`` (1 MiB): the window tax amortizes per
    slice exactly as it does for a real solo stream — smaller probe
    slices would overweight the window and veto a fast device.  Cached
    after first call; ``pipeline.md5_backend=auto`` compares this
    against the host lane rate and picks the winner
    (hashing/md5fast.py)."""
    global _RATE
    if _RATE is not None:
        return _RATE
    if not available():
        _RATE = 0.0
        return _RATE
    try:
        buf = b"\0" * (kib_per_slice * 1024)

        def one():
            h = MD5Device()
            for _ in range(slices):
                h.update(buf)
            h.digest()

        one()                                    # compile + warm
        t0 = time.perf_counter()
        reps = 3
        for _ in range(reps):
            one()
        dt = time.perf_counter() - t0
        _RATE = reps * slices * len(buf) / dt / 2**30
    except Exception:  # noqa: BLE001 — a broken probe means "slow"
        _RATE = 0.0
    return _RATE
