"""SipHash-2-4 — object-name -> erasure-set distribution hash.

Reference: cmd/erasure-sets.go:629 sipHashMod (dchest/siphash dep) keyed by
the deployment ID.  Bit-identical is required for on-disk layout
compatibility.  Native C path with a pure-Python fallback.
"""

from __future__ import annotations

import ctypes
import struct

from . import highwayhash as _hh

_M64 = (1 << 64) - 1


def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & _M64


def _py_siphash24(k0: int, k1: int, data: bytes) -> int:
    v0 = 0x736F6D6570736575 ^ k0
    v1 = 0x646F72616E646F6D ^ k1
    v2 = 0x6C7967656E657261 ^ k0
    v3 = 0x7465646279746573 ^ k1

    def rnd():
        nonlocal v0, v1, v2, v3
        v0 = (v0 + v1) & _M64
        v1 = _rotl(v1, 13) ^ v0
        v0 = _rotl(v0, 32)
        v2 = (v2 + v3) & _M64
        v3 = _rotl(v3, 16) ^ v2
        v0 = (v0 + v3) & _M64
        v3 = _rotl(v3, 21) ^ v0
        v2 = (v2 + v1) & _M64
        v1 = _rotl(v1, 17) ^ v2
        v2 = _rotl(v2, 32)

    n = len(data)
    end = n - (n % 8)
    for i in range(0, end, 8):
        m = struct.unpack_from("<Q", data, i)[0]
        v3 ^= m
        rnd()
        rnd()
        v0 ^= m
    b = (n << 56) & _M64
    for i in range(n % 8):
        b |= data[end + i] << (8 * i)
    v3 ^= b
    rnd()
    rnd()
    v0 ^= b
    v2 ^= 0xFF
    for _ in range(4):
        rnd()
    return (v0 ^ v1 ^ v2 ^ v3) & _M64


def siphash24(data: bytes | str, key: bytes) -> int:
    """SipHash-2-4 of data under a 16-byte key."""
    if isinstance(data, str):
        data = data.encode()
    k0, k1 = struct.unpack("<2Q", key)
    lib = _hh._get_lib()
    if lib is not None:
        if not hasattr(lib, "_sip_ready"):
            lib.mt_siphash24.argtypes = [
                ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_char_p, ctypes.c_size_t]
            lib.mt_siphash24.restype = ctypes.c_uint64
            lib._sip_ready = True
        return int(lib.mt_siphash24(k0, k1, data, len(data)))
    return _py_siphash24(k0, k1, data)


def sip_hash_mod(key: str, cardinality: int, id_bytes: bytes) -> int:
    """cmd/erasure-sets.go:629 sipHashMod: set index for an object name."""
    if cardinality <= 0:
        return -1
    return siphash24(key, id_bytes[:16]) % cardinality
