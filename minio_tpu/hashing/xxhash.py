"""xxHash64 (cespare/xxhash v2.1.1 equivalent, go.mod:16).

Used by the data-update tracker's bloom filter
(cmd/data-update-tracker.go) — bit-identical with the reference's
xxh64 so persisted filters stay portable.  Pure Python; the filter
hashes short object paths, so throughput is not on any hot path.
"""

PRIME1 = 0x9E3779B185EBCA87
PRIME2 = 0xC2B2AE3D27D4EB4F
PRIME3 = 0x165667B19E3779F9
PRIME4 = 0x85EBCA77C2B2AE63
PRIME5 = 0x27D4EB2F165667C5

_M = (1 << 64) - 1


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M


def _round(acc: int, inp: int) -> int:
    acc = (acc + inp * PRIME2) & _M
    return (_rotl(acc, 31) * PRIME1) & _M


def _merge_round(acc: int, val: int) -> int:
    acc ^= _round(0, val)
    return (acc * PRIME1 + PRIME4) & _M


def xxh64(data: bytes, seed: int = 0) -> int:
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + PRIME1 + PRIME2) & _M
        v2 = (seed + PRIME2) & _M
        v3 = seed
        v4 = (seed - PRIME1) & _M
        while i <= n - 32:
            v1 = _round(v1, int.from_bytes(data[i:i + 8], "little"))
            v2 = _round(v2, int.from_bytes(data[i + 8:i + 16], "little"))
            v3 = _round(v3, int.from_bytes(data[i + 16:i + 24], "little"))
            v4 = _round(v4, int.from_bytes(data[i + 24:i + 32], "little"))
            i += 32
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) +
             _rotl(v4, 18)) & _M
        h = _merge_round(h, v1)
        h = _merge_round(h, v2)
        h = _merge_round(h, v3)
        h = _merge_round(h, v4)
    else:
        h = (seed + PRIME5) & _M
    h = (h + n) & _M
    while i <= n - 8:
        k = _round(0, int.from_bytes(data[i:i + 8], "little"))
        h ^= k
        h = (_rotl(h, 27) * PRIME1 + PRIME4) & _M
        i += 8
    if i <= n - 4:
        h ^= (int.from_bytes(data[i:i + 4], "little") * PRIME1) & _M
        h = (_rotl(h, 23) * PRIME2 + PRIME3) & _M
        i += 4
    while i < n:
        h ^= (data[i] * PRIME5) & _M
        h = (_rotl(h, 11) * PRIME1) & _M
        i += 1
    h ^= h >> 33
    h = (h * PRIME2) & _M
    h ^= h >> 29
    h = (h * PRIME3) & _M
    h ^= h >> 32
    return h
