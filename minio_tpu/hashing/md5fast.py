"""Multi-lane native MD5 — the strict-compat ETag engine
(native/md5mb.cc via ctypes; the md5-simd role of the reference's PUT
path, SURVEY §2.4).

Strict S3 compatibility pins the ETag algorithm to MD5, and MD5 is a
serial dependency chain — one stream cannot go faster than one core's
chain latency.  What CAN go faster is *many* streams: concurrent PUTs
and multipart parts each carry an independent digest, and interleaving
their compression rounds in one native call fills the issue slots a
single chain leaves idle.  Three layers here:

  * ``MD5Fast`` — a hashlib-compatible digest object over the native
    single-stream core (ILP-tuned, GIL-free updates so the ETag truly
    runs beside erasure encode and the drive writer queues);
  * ``LaneScheduler`` — a combining scheduler: concurrent ``update``
    calls from different streams coalesce into one N-lane multi-buffer
    native call (``pipeline.md5_lanes`` bounds N, live-reloadable).
    The first caller becomes the combiner and drains the queue; later
    callers park until their chunk is hashed.  With one stream in
    flight the scheduler degenerates to the plain fast core — lanes
    are an opportunistic win, never a wait;
  * graceful fallback — no compiler / ``MT_MD5=hashlib`` / absent
    ``.so`` all land on ``hashlib.md5``; digests are bit-identical
    either way (pinned across lane counts and tail lengths by
    tests/test_md5fast.py).

Counters (doc-linted in docs/observability.md): ``mt_md5_lane_batches_
total{lanes=}`` per combined native call, ``mt_md5_native_bytes_total``
for scheduler-routed bytes, ``mt_md5_fallback_total`` when native was
requested but unavailable.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import threading
import time
from ..utils.locktrace import mtlock

_NATIVE_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "md5mb.cc")
_NATIVE_SO = os.path.join(os.path.dirname(_NATIVE_SRC), "build",
                          "libmtmd5.so")

_LIB = None
_LIB_TRIED = False
_STATE_SIZE = 0
_load_lock = mtlock("md5.native-load")


def _get_lib():
    global _LIB, _LIB_TRIED, _STATE_SIZE
    if _LIB_TRIED:
        return _LIB
    with _load_lock:
        if _LIB_TRIED:
            return _LIB
        from ..utils import nativelib
        lib = nativelib.load(_NATIVE_SRC, _NATIVE_SO)
        if lib is not None:
            try:
                lib.mt_md5_state_size.restype = ctypes.c_size_t
                lib.mt_md5_init.argtypes = [ctypes.c_char_p]
                lib.mt_md5_update.argtypes = [
                    ctypes.c_char_p, ctypes.c_void_p, ctypes.c_size_t]
                lib.mt_md5_final.argtypes = [ctypes.c_char_p,
                                             ctypes.c_char_p]
                lib.mt_md5_oneshot.argtypes = [
                    ctypes.c_void_p, ctypes.c_size_t, ctypes.c_char_p]
                lib.mt_md5mb_update.argtypes = [
                    ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
                    ctypes.POINTER(ctypes.c_void_p),
                    ctypes.POINTER(ctypes.c_size_t)]
                _STATE_SIZE = int(lib.mt_md5_state_size())
            except Exception:  # noqa: BLE001 — fall back to hashlib
                lib = None
        _LIB = lib
        _LIB_TRIED = True
        return _LIB


def _mode() -> str:
    """MT_MD5=hashlib forces the stdlib; MT_MD5=native (the default)
    uses the .so when it loads."""
    return os.environ.get("MT_MD5", "native").strip().lower()


def available() -> bool:
    return _mode() != "hashlib" and _get_lib() is not None


# -- backend ladder ---------------------------------------------------------
#
# ``pipeline.md5_backend`` (kvconfig, live-reloadable through
# reload_pipeline_config) selects the strict-ETag engine:
#
#   device  -> hashing/md5_device.MD5Device: bulk blocks batched onto
#              the accelerator through the md5 combining bucket
#              (parallel/batcher.py); falls to the next rung (counted
#              in mt_md5_device_fallback_total) when no device
#   native  -> MD5Fast over native/md5mb.cc + the host LaneScheduler
#   hashlib -> the stdlib (also forced by MT_MD5=hashlib, which
#              outranks the knob — the operator kill switch)
#   auto    -> MEASURED choice: the device rung only when its probed
#              end-to-end rate (md5_device.device_rate_gibps, transfer
#              included) beats the host core by a margin.  A TPU
#              behind a slow tunnel must lose this race — the platform
#              name alone says nothing about H2D bandwidth.

_BACKEND = "auto"
_AUTO_CHOICE: str | None = None
_AUTO_MARGIN = 1.25


def set_backend(name: str) -> None:
    """Install the configured backend (reload_pipeline_config hook);
    unknown names keep the current value.  Changing the backend resets
    the cached auto decision."""
    global _BACKEND, _AUTO_CHOICE
    name = (name or "").strip().lower()
    if name in ("auto", "device", "native", "hashlib") \
            and name != _BACKEND:
        # same-name reloads (every SetConfigKV of an unrelated
        # pipeline knob, every layer construction) must NOT discard a
        # settled measured auto decision — that would thrash strict
        # ETags back to the host rung and respawn probe threads
        _BACKEND = name
        _AUTO_CHOICE = None


def _host_rate_gibps() -> float:
    """One-shot probe of the host single-stream rate (native core when
    present, hashlib otherwise) — the bar the device must clear."""
    import hashlib as _hl
    buf = b"\0" * (1 << 20)
    fn = (lambda: MD5Fast(buf)) if available() else \
        (lambda: _hl.md5(buf))
    fn()                                         # warm
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        fn()
    return reps * len(buf) / (time.perf_counter() - t0) / 2**30


def _resolve_backend() -> str:
    """The effective rung for this digest: env override first, then
    the knob, with ``auto`` resolved (and cached) by measurement."""
    global _AUTO_CHOICE
    env = os.environ.get("MT_MD5")
    env = env.strip().lower() if env is not None else None
    if env == "hashlib":
        return "hashlib"
    be = _BACKEND
    if env in ("device", "native"):              # MT_MD5 pins a rung
        be = env
    if be != "auto":
        return be
    if _AUTO_CHOICE is None:
        from . import md5_device
        if not md5_device.available():
            _AUTO_CHOICE = "native"
        else:
            # probe OFF the request path: device_rate_gibps pays an
            # XLA compile plus ~20 MiB of benchmark hashing — charged
            # to a background thread, not to the first strict PUT of
            # the process.  Until the probe lands, auto serves the
            # host rung (always correct, never slower than today).
            _start_auto_probe()
            return "native"
    return _AUTO_CHOICE


_probe_lock = mtlock("md5.auto-probe")
_probe_started = False


def _start_auto_probe() -> None:
    global _probe_started
    with _probe_lock:
        if _probe_started:
            return
        _probe_started = True

    def probe():
        global _AUTO_CHOICE, _probe_started
        try:
            from . import md5_device
            dev = md5_device.device_rate_gibps()
            host = _host_rate_gibps()
            choice = "device" if dev > host * _AUTO_MARGIN \
                else "native"
        except Exception:  # noqa: BLE001 — a broken probe means host
            choice = "native"
        with _probe_lock:
            if _AUTO_CHOICE is None:
                _AUTO_CHOICE = choice
            _probe_started = False

    threading.Thread(target=probe, daemon=True,
                     name="mt-md5-calibrate").start()


def _buf_addr(data) -> tuple[int, int, object]:
    """(address, length, keepalive) for any contiguous buffer without
    copying (bytes, bytearray, memoryview slices, numpy rows)."""
    if isinstance(data, bytes):
        return (ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value
                or 0, len(data), data)
    import numpy as np
    arr = data if isinstance(data, np.ndarray) \
        else np.frombuffer(data, dtype=np.uint8)
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return arr.ctypes.data, arr.size, arr


class MD5Fast:
    """hashlib.md5-compatible object over the native core.  ``digest``
    finalizes a copy of the state, so the stream stays usable (the
    same contract as the stdlib)."""

    name = "md5"
    digest_size = 16
    block_size = 64

    __slots__ = ("_st", "_lib")

    def __init__(self, data=b""):
        self._lib = _get_lib()
        self._st = ctypes.create_string_buffer(_STATE_SIZE)
        self._lib.mt_md5_init(self._st)
        if data:
            self.update(data)

    def update(self, data) -> None:
        addr, n, _keep = _buf_addr(data)
        if n:
            self._lib.mt_md5_update(self._st, addr, n)

    def digest(self) -> bytes:
        cp = ctypes.create_string_buffer(self._st.raw, _STATE_SIZE + 1)
        out = ctypes.create_string_buffer(16)
        self._lib.mt_md5_final(cp, out)
        return out.raw[:16]

    def hexdigest(self) -> str:
        return self.digest().hex()

    def copy(self) -> "MD5Fast":
        c = MD5Fast.__new__(MD5Fast)
        c._lib = self._lib
        c._st = ctypes.create_string_buffer(self._st.raw, _STATE_SIZE + 1)
        return c


def md5(data=b""):
    """Digest factory for the ETag hot path, walking the backend
    ladder (see ``set_backend``): device -> native -> hashlib, each
    rung falling through with its fallback counted."""
    be = _resolve_backend()
    if be == "device":
        from . import md5_device
        if md5_device.available():
            return md5_device.MD5Device(data)
        from ..admin.metrics import GLOBAL as _mtr
        _mtr.inc("mt_md5_device_fallback_total")
    if be != "hashlib" and available():
        return MD5Fast(data)
    if be != "hashlib":
        from ..admin.metrics import GLOBAL as _mtr
        _mtr.inc("mt_md5_fallback_total")
    return hashlib.md5(bytes(data) if not isinstance(
        data, (bytes, bytearray, memoryview)) else data)


class LaneScheduler:
    """Combining N-lane scheduler: concurrent streams' chunk updates
    coalesce into one multi-buffer native call.

    The first thread to arrive becomes the combiner; it drains the
    pending queue in batches of up to ``lanes`` and hashes each batch
    with ONE GIL-free ``mt_md5mb_update``.  Later arrivals park on an
    event until their chunk is done (their pool thread yields the core
    to encode/writers meanwhile).  A stream's own updates are ordered
    by its caller (the _md5_link chain waits on the previous link), so
    a given digest never appears twice in one batch."""

    def __init__(self, lanes: int | None = None):
        self._mu = mtlock("md5.sched")
        self._q: list[list] = []        # [h, chunk, event, exc]
        self._combining = False
        self._lanes = lanes

    def lanes(self) -> int:
        if self._lanes is None:
            try:
                from ..utils.kvconfig import Config
                self._lanes = max(1, int(Config().get("pipeline",
                                                      "md5_lanes")))
            except Exception:  # noqa: BLE001 — default below
                self._lanes = 4
        return self._lanes

    def set_lanes(self, n: int) -> None:
        self._lanes = max(1, int(n))

    def update(self, h, chunk) -> None:
        """Hash ``chunk`` into ``h``, sharing lanes with whatever other
        streams are updating right now.  Falls through to a plain
        update for hashlib objects (native absent) and when lanes are
        disabled."""
        if not isinstance(h, MD5Fast) or self.lanes() <= 1:
            h.update(chunk)
            return
        item = [h, chunk, threading.Event(), None]
        with self._mu:
            self._q.append(item)
            lead = not self._combining
            if lead:
                self._combining = True
        if not lead:
            item[2].wait()
            if item[3] is not None:
                raise item[3]
            return
        # combiner: drain the queue (our own item included), then
        # release the role so the next arrival leads a new round.  The
        # combiner's OWN chunk rides one of the batches below — its
        # exc slot must be re-checked on the way out exactly like a
        # parked caller's, else a failed batch would silently skip
        # this stream's chunk and serve a wrong ETag.
        try:
            while True:
                with self._mu:
                    batch = self._q[:self.lanes()]
                    del self._q[:len(batch)]
                    if not batch:
                        self._combining = False
                        break
                lanes = self.lanes()
                if len(batch) < lanes:
                    # GIL yields before an under-full round: streams
                    # woken by the previous round's events are runnable
                    # but unscheduled, and without the yields a fresh
                    # combiner races ahead with 1-lane rounds forever
                    # (measured: alternating 1/3-lane batches instead
                    # of steady 4-lane).  A yield is not a wait — a
                    # genuinely lone stream pays a few no-op syscalls
                    # (~µs) per ~1 MiB slice (~ms).
                    for _ in range(lanes - len(batch)):
                        time.sleep(0)
                        with self._mu:
                            extra = self._q[:lanes - len(batch)]
                            del self._q[:len(extra)]
                        batch = batch + extra
                        if len(batch) >= lanes:
                            break
                self._run_batch(batch)
        except BaseException:
            with self._mu:
                self._combining = False
            raise
        if item[3] is not None:
            raise item[3]

    def _run_batch(self, batch: list[list]) -> None:
        from ..admin.metrics import GLOBAL as _mtr
        n = len(batch)
        try:
            if n == 1:
                h, chunk, _, _ = batch[0]
                h.update(chunk)
                nbytes = len(memoryview(chunk).cast("B")) \
                    if not isinstance(chunk, bytes) else len(chunk)
            else:
                lib = _get_lib()
                states = (ctypes.c_void_p * n)()
                ptrs = (ctypes.c_void_p * n)()
                lens = (ctypes.c_size_t * n)()
                keep = []
                for i, it in enumerate(batch):
                    states[i] = ctypes.addressof(it[0]._st)
                    addr, ln, ka = _buf_addr(it[1])
                    ptrs[i] = addr
                    lens[i] = ln
                    keep.append(ka)
                lib.mt_md5mb_update(n, states, ptrs, lens)
                nbytes = sum(lens[i] for i in range(n))
            _mtr.inc("mt_md5_lane_batches_total", {"lanes": str(n)})
            _mtr.inc("mt_md5_native_bytes_total", value=float(nbytes))
        except Exception as e:  # noqa: BLE001 — surface on each caller
            for it in batch:
                it[3] = e
        finally:
            for it in batch:
                it[2].set()


SCHED = LaneScheduler()

# scheduler-routed oneshot slice size: big enough that per-call
# overhead vanishes, small enough that two concurrent 4 MiB oneshots
# interleave across many batches instead of missing each other
ONESHOT_SLICE = 1 << 20


def md5_of(data):
    """Whole-buffer digest routed through the lane scheduler in
    ONESHOT_SLICE steps, so concurrent single-part PUTs' ETag passes
    share lanes (the overlapped bytes-PUT path submits this on the
    pool).  Returns the digest object (hexdigest() for the ETag)."""
    h = md5()
    if type(h).__name__ == "MD5Device":
        # device digests combine through the md5 bucket instead of the
        # host lane scheduler; slicing still interleaves concurrent
        # oneshots across batched dispatches
        mv = memoryview(data).cast("B")
        for off in range(0, len(mv), ONESHOT_SLICE):
            h.update(mv[off:off + ONESHOT_SLICE])
        return h
    if not isinstance(h, MD5Fast):
        h.update(bytes(data) if not isinstance(
            data, (bytes, bytearray, memoryview)) else data)
        return h
    mv = memoryview(data).cast("B")
    for off in range(0, len(mv), ONESHOT_SLICE):
        SCHED.update(h, mv[off:off + ONESHOT_SLICE])
    return h
