"""Bitrot protection layer — per-shard-block hash framing.

Reference behavior (cmd/bitrot.go, cmd/bitrot-streaming.go, cmd/bitrot-whole.go):

  * four algorithms: SHA256, BLAKE2b-512, HighwayHash256 (whole-file) and
    HighwayHash256S (streaming, the default) -- cmd/bitrot.go:33-38;
  * the streaming format interleaves ``hash(block) || block`` for each
    shard-size block in the shard file (cmd/bitrot-streaming.go:46-58);
  * readers verify every block hash on ReadAt and surface errFileCorrupt on
    mismatch (cmd/bitrot-streaming.go:115-158);
  * bitrotShardFileSize = ceil(size/shardSize)*hashLen + size for streaming
    algorithms, size otherwise (cmd/bitrot.go:140-145).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import BinaryIO

from .highwayhash import MAGIC_KEY, HighwayHash256, hh256
from ..ops.gf8 import ceil_frac

# algorithm ids follow the reference's iota order (cmd/bitrot-whole.go deps):
SHA256 = "sha256"
BLAKE2B512 = "blake2b"
HIGHWAYHASH256 = "highwayhash256"
HIGHWAYHASH256S = "highwayhash256S"
DEFAULT_BITROT_ALGORITHM = HIGHWAYHASH256S

_ALGORITHMS = {SHA256, BLAKE2B512, HIGHWAYHASH256, HIGHWAYHASH256S}


class BitrotError(IOError):
    """errFileCorrupt analog: stored hash does not match content."""


def is_streaming(algo: str) -> bool:
    return algo == HIGHWAYHASH256S


def available(algo: str) -> bool:
    return algo in _ALGORITHMS


def new_hash(algo: str):
    """BitrotAlgorithm.New (cmd/bitrot.go:41-58)."""
    if algo == SHA256:
        return hashlib.sha256()
    if algo == BLAKE2B512:
        return hashlib.blake2b(digest_size=64)
    if algo in (HIGHWAYHASH256, HIGHWAYHASH256S):
        return HighwayHash256(MAGIC_KEY)
    raise ValueError(f"unsupported bitrot algorithm {algo!r}")


def digest_size(algo: str) -> int:
    return new_hash(algo).digest_size


def hash_block(algo: str, block: bytes) -> bytes:
    if algo in (HIGHWAYHASH256, HIGHWAYHASH256S):
        return hh256(block)  # native one-shot fast path
    h = new_hash(algo)
    h.update(block)
    return h.digest()


def bitrot_shard_file_size(size: int, shard_size: int, algo: str) -> int:
    """On-disk size of a shard file with bitrot protection
    (cmd/bitrot.go:140-145)."""
    if not is_streaming(algo):
        return size
    return ceil_frac(size, shard_size) * digest_size(algo) + size


def bitrot_shard_file_offset(offset: int, shard_size: int, algo: str) -> int:
    """Logical shard offset -> physical offset in the framed stream
    (cmd/bitrot-streaming.go:126)."""
    if not is_streaming(algo):
        return offset
    return (offset // shard_size) * digest_size(algo) + offset


def streaming_encode(data: bytes, shard_size: int,
                     algo: str = DEFAULT_BITROT_ALGORITHM) -> bytes:
    """Frame a whole shard file: hash || block per shard_size block."""
    if not is_streaming(algo):     # only highwayhash256S streams
        # whole-file algos store the shard unframed — coerce to bytes so
        # downstream consumers (msgpack inline_data, RPC bodies) never
        # see a numpy row
        return data if isinstance(data, bytes) else \
            bytes(memoryview(data).cast("B"))
    if len(data) == 0:
        return b""
    # one GIL-free native pass: hash + interleave together
    from .highwayhash import hh256_frame
    return hh256_frame(data, shard_size)


def _interleave(data: bytes, shard_size: int, hashes) -> bytes:
    out = bytearray()
    for i, h in enumerate(hashes):
        out += bytes(h)
        out += data[i * shard_size:(i + 1) * shard_size]
    return bytes(out)


def streaming_encode_batch(shards, shard_size: int,
                           algo: str = DEFAULT_BITROT_ALGORITHM,
                           use_device: bool = False) -> list[bytes]:
    """Frame a full stripe of equal-length shard files at once.

    With use_device, the per-block HighwayHash runs ON the TPU
    (ops/hh_kernels), fused after the erasure encode so parity AND
    bitrot digests come out of one device pipeline (BASELINE config 5).
    Falls back to the host C path on any device failure."""
    if not is_streaming(algo):
        return [bytes(bytearray(s)) for s in shards]
    if use_device and algo == HIGHWAYHASH256S and shards:
        try:
            import time as _time

            from ..obs import trace as _trace
            if not _trace.active():
                return _streaming_encode_batch_device(shards, shard_size)
            # fused-hash span (trace type ``tpu``): the device-side
            # HighwayHash leg of the fused encode+hash pipeline.
            # Monotonic duration, wall clock only for the timestamp.
            t0 = _time.monotonic_ns()
            out = _streaming_encode_batch_device(shards, shard_size)
            try:
                # span bookkeeping must never reroute the data path:
                # an observability error here would otherwise be
                # swallowed by the DEVICE-failure fallback below and
                # throw away a completed device result
                dt = _time.monotonic_ns() - t0
                nbytes = sum(getattr(s, "nbytes", len(s))
                             for s in shards)
                _trace.publish_span(_trace.make_span(
                    "tpu", "tpu.fused-hash",
                    start_ns=_trace.now_ns() - dt,
                    duration_ns=dt, input_bytes=nbytes,
                    detail={"op": "fused-hash", "shards": len(shards),
                            "shardSize": shard_size}))
            except Exception:  # noqa: BLE001 — tracing must never
                pass           # fail the hash path
            return out
        except Exception:  # noqa: BLE001 — host path is always correct
            pass
    # streaming_encode takes any contiguous buffer zero-copy (numpy
    # shard rows included) — don't round-trip through bytes()
    return [streaming_encode(s, shard_size, algo) for s in shards]


def fill_framed(framed2d, shard_size: int,
                algo: str = DEFAULT_BITROT_ALGORITHM) -> bool:
    """Fill digest slots of pre-framed shard rows IN PLACE.

    framed2d: (n_shards, framed_len) uint8 laid out by
    Erasure.encode_object_framed ([32B zeroed digest][block] frames).
    Returns False when the native hash library is unavailable — the
    caller then uses the copying streaming_encode_batch path instead."""
    if algo != HIGHWAYHASH256S:
        return False
    from .highwayhash import hh256_fill
    F = 32 + shard_size
    if getattr(framed2d, "ndim", 1) == 2 and framed2d.shape[1] % F == 0 \
            and framed2d.flags["C_CONTIGUOUS"]:
        # no short tail frame: row boundaries fall on frame boundaries,
        # so the whole 2D buffer is one valid frame sequence — hash all
        # k+m rows in a single GIL-free native pass
        return hh256_fill(framed2d.reshape(-1), shard_size)
    for row in framed2d:
        if not hh256_fill(row, shard_size):
            return False
    return True


def _device_hh256_batch(blocks):
    """Best device formulation: single fused pallas kernel on TPU,
    lax.scan packet loop elsewhere (both bit-identical)."""
    import jax
    if jax.default_backend() == "tpu":
        from ..ops import hh_pallas
        return hh_pallas.hh256_batch(blocks)
    from ..ops import hh_kernels
    return hh_kernels.hh256_batch(blocks)


def _streaming_encode_batch_device(shards, shard_size: int) -> list[bytes]:
    import numpy as np
    arrs = [np.asarray(bytearray(s), dtype=np.uint8) for s in shards]
    L = len(arrs[0])
    if L == 0:
        return [b"" for _ in arrs]
    if any(len(a) != L for a in arrs):
        raise ValueError("shard lengths differ")
    nblocks = ceil_frac(L, shard_size)
    full, rem = divmod(L, shard_size)
    stacked = np.stack(arrs)                       # (S, L)
    digests: list[list[bytes]] = [[] for _ in arrs]
    if full:
        blocks = stacked[:, :full * shard_size].reshape(-1, shard_size)
        hs = np.asarray(_device_hh256_batch(blocks))
        hs = hs.reshape(len(arrs), full, 32)
        for si in range(len(arrs)):
            digests[si] = [hs[si, b].tobytes() for b in range(full)]
    if rem:
        tails = stacked[:, full * shard_size:]
        hs = np.asarray(_device_hh256_batch(tails))
        for si in range(len(arrs)):
            digests[si].append(hs[si].tobytes())
    assert all(len(d) == nblocks for d in digests)
    return [_interleave(arrs[si].tobytes(), shard_size, digests[si])
            for si in range(len(arrs))]


class StreamingBitrotWriter:
    """Interleaves hash||block into a file-like sink
    (cmd/bitrot-streaming.go:39-58).  Each write() must be exactly one
    shard-size block (the last may be short), as in the reference where the
    erasure encoder hands one shard-block per stripe."""

    def __init__(self, sink: BinaryIO, algo: str = DEFAULT_BITROT_ALGORITHM):
        self.sink = sink
        self.algo = algo

    def write(self, block: bytes) -> int:
        if len(block) == 0:
            return 0
        self.sink.write(hash_block(self.algo, block))
        self.sink.write(block)
        return len(block)


class StreamingBitrotReader:
    """Verified ReadAt over a framed shard stream
    (cmd/bitrot-streaming.go:92-158).

    ``read_at(offset, length)``: offset must be shard_size aligned (logical,
    unframed coordinates); every covered block's hash is verified."""

    def __init__(self, framed: bytes | memoryview, shard_size: int,
                 algo: str = DEFAULT_BITROT_ALGORITHM):
        self.data = memoryview(framed)
        self.shard_size = shard_size
        self.algo = algo
        self.hash_len = digest_size(algo)

    def read_at(self, offset: int, length: int) -> bytes:
        if not is_streaming(self.algo):
            # whole-file algorithms carry no interleaved hashes; verification
            # is done once over the full file via BitrotVerifier
            return bytes(self.data[offset:offset + length])
        if offset % self.shard_size != 0:
            raise ValueError("offset must be aligned to shard size")
        out = bytearray()
        pos = (offset // self.shard_size) * self.hash_len + offset
        remaining = length
        while remaining > 0:
            want = min(self.shard_size, remaining)
            h = bytes(self.data[pos:pos + self.hash_len])
            if len(h) < self.hash_len:
                raise BitrotError("short read: missing block hash")
            pos += self.hash_len
            block = bytes(self.data[pos:pos + want])
            if len(block) < want:
                raise BitrotError("short read: truncated block")
            pos += len(block)
            if hash_block(self.algo, block) != h:
                raise BitrotError("content hash mismatch")
            out += block
            remaining -= want
        return bytes(out)


def verify_extract(framed, shard_size: int, length: int,
                   algo: str = DEFAULT_BITROT_ALGORITHM):
    """Verify a whole framed shard and extract its payload — the GET
    hot path (cmd/bitrot-streaming.go ReadAt, whole-shard case).

    One GIL-free native digest pass over the frame plus one strided
    numpy copy for the payload, instead of per-block Python hashing
    with three intermediate copies.  Returns a uint8 array of
    ``length`` payload bytes, or None when the fast path does not
    apply (non-HH256S algo / native lib missing) — caller falls back
    to StreamingBitrotReader.
    """
    if algo != HIGHWAYHASH256S:
        return None
    from .highwayhash import hh256_verify_framed
    import numpy as np
    arr = np.frombuffer(framed, dtype=np.uint8) \
        if not isinstance(framed, np.ndarray) else framed
    bad = hh256_verify_framed(arr, shard_size)
    if bad is None:
        return None
    if bad:
        raise BitrotError(f"content hash mismatch (block {bad})")
    F = 32 + shard_size
    nfull = arr.size // F
    head = arr[:nfull * F].reshape(nfull, F)[:, 32:]   # strided view
    if nfull * shard_size >= length:
        return head.reshape(-1)[:length].copy()
    # Caller-declared length comes from xl.meta — never trust it past
    # what the digest-verified frame actually holds, or the tail copy
    # below raises a broadcast ValueError that escapes the caller's
    # BitrotError handling and surfaces as a 500 instead of FileCorrupt.
    tail = arr[nfull * F + 32:]                        # short last block
    if nfull * shard_size + tail.size < length:
        raise BitrotError(
            f"truncated frame: {nfull * shard_size + tail.size} payload "
            f"bytes present, {length} declared")
    out = np.empty(length, dtype=np.uint8)
    out[:nfull * shard_size] = head.reshape(-1)
    out[nfull * shard_size:] = tail[:length - nfull * shard_size]
    return out


@dataclass
class BitrotVerifier:
    """Whole-file verifier (cmd/bitrot.go:77-85)."""
    algorithm: str
    sum: bytes

    def verify(self, data: bytes) -> bool:
        return hash_block(self.algorithm, data) == self.sum


class WholeBitrotWriter:
    """Whole-file bitrot: raw bytes to sink, running hash kept for metadata
    (cmd/bitrot-whole.go:29-59)."""

    def __init__(self, sink: BinaryIO, algo: str):
        self.sink = sink
        self._h = new_hash(algo)

    def write(self, p: bytes) -> int:
        self._h.update(p)
        self.sink.write(p)
        return len(p)

    def sum(self) -> bytes:
        return self._h.digest()
