/* Portable HighwayHash-256 — the bitrot checksum of the reference
 * (minio/highwayhash dep; used via cmd/bitrot.go:41-53 with a fixed magic
 * key).  Written from the published HighwayHash algorithm (portable
 * formulation); validated against the public HighwayHash64 test vectors in
 * tests/test_bitrot.py.
 *
 * This is the framework's host-native hashing core: a C analog of the
 * reference's AVX2 assembly module.  One-shot and streaming entry points,
 * plus a batch call for hashing many shard blocks per dispatch.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define MT_HH_X86 1
#endif

typedef struct {
  uint64_t v0[4], v1[4], mul0[4], mul1[4];
} HHState;

static const uint64_t kInitMul0[4] = {
    0xdbe6d5d5fe4cce2full, 0xa4093822299f31d0ull,
    0x13198a2e03707344ull, 0x243f6a8885a308d3ull};
static const uint64_t kInitMul1[4] = {
    0x3bd39e10cb0ef593ull, 0xc0acf169b5f18a8cull,
    0xbe5466cf34e90c6cull, 0x452821e638d01377ull};

static void hh_reset(HHState* s, const uint64_t key[4]) {
  for (int i = 0; i < 4; ++i) {
    s->mul0[i] = kInitMul0[i];
    s->mul1[i] = kInitMul1[i];
    s->v0[i] = kInitMul0[i] ^ key[i];
    s->v1[i] = kInitMul1[i] ^ ((key[i] >> 32) | (key[i] << 32));
  }
}

static void zipper_merge_and_add(const uint64_t v1, const uint64_t v0,
                                 uint64_t* add1, uint64_t* add0) {
  *add0 += (((v0 & 0xff000000ull) | (v1 & 0xff00000000ull)) >> 24) |
           (((v0 & 0xff0000000000ull) | (v1 & 0xff000000000000ull)) >> 16) |
           (v0 & 0xff0000ull) | ((v0 & 0xff00ull) << 32) |
           ((v1 & 0xff00000000000000ull) >> 8) | (v0 << 56);
  *add1 += (((v1 & 0xff000000ull) | (v0 & 0xff00000000ull)) >> 24) |
           (v1 & 0xff0000ull) | ((v1 & 0xff0000000000ull) >> 16) |
           ((v1 & 0xff00ull) << 24) | ((v0 & 0xff000000000000ull) >> 8) |
           ((v1 & 0xffull) << 48) | (v0 & 0xff00000000000000ull);
}

static uint64_t read_le64(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8); /* little-endian hosts only (x86/arm LE) */
  return v;
}

static void hh_update_lanes(HHState* s, const uint64_t lanes[4]) {
  int i;
  for (i = 0; i < 4; ++i) s->v1[i] += s->mul0[i] + lanes[i];
  for (i = 0; i < 4; ++i)
    s->mul0[i] ^= (s->v1[i] & 0xffffffffull) * (s->v0[i] >> 32);
  for (i = 0; i < 4; ++i) s->v0[i] += s->mul1[i];
  for (i = 0; i < 4; ++i)
    s->mul1[i] ^= (s->v0[i] & 0xffffffffull) * (s->v1[i] >> 32);
  zipper_merge_and_add(s->v1[1], s->v1[0], &s->v0[1], &s->v0[0]);
  zipper_merge_and_add(s->v1[3], s->v1[2], &s->v0[3], &s->v0[2]);
  zipper_merge_and_add(s->v0[1], s->v0[0], &s->v1[1], &s->v1[0]);
  zipper_merge_and_add(s->v0[3], s->v0[2], &s->v1[3], &s->v1[2]);
}

static void hh_update_packet(HHState* s, const uint8_t* packet) {
  uint64_t lanes[4];
  for (int i = 0; i < 4; ++i) lanes[i] = read_le64(packet + 8 * i);
  hh_update_lanes(s, lanes);
}

static void rotate_32_by(uint32_t count, uint64_t lanes[4]) {
  for (int i = 0; i < 4; ++i) {
    uint32_t half0 = (uint32_t)(lanes[i] & 0xffffffffull);
    uint32_t half1 = (uint32_t)(lanes[i] >> 32);
    lanes[i] = ((uint64_t)((half0 << count) | (half0 >> (32 - count)))) |
               (((uint64_t)((half1 << count) | (half1 >> (32 - count)))) << 32);
  }
}

static void hh_update_remainder(HHState* s, const uint8_t* bytes,
                                const size_t size_mod32) {
  int i;
  const size_t size_mod4 = size_mod32 & 3;
  const uint8_t* remainder = bytes + (size_mod32 & ~3u);
  uint8_t packet[32] = {0};
  for (i = 0; i < 4; ++i)
    s->v0[i] += ((uint64_t)size_mod32 << 32) + size_mod32;
  rotate_32_by((uint32_t)size_mod32, s->v1);
  for (i = 0; i < (int)(remainder - bytes); ++i) packet[i] = bytes[i];
  if (size_mod32 & 16) {
    for (i = 0; i < 4; ++i)
      packet[28 + i] = remainder[i + (int)size_mod4 - 4];
  } else if (size_mod4) {
    packet[16 + 0] = remainder[0];
    packet[16 + 1] = remainder[size_mod4 >> 1];
    packet[16 + 2] = remainder[size_mod4 - 1];
  }
  hh_update_packet(s, packet);
}

static void permute_and_update(HHState* s) {
  uint64_t permuted[4];
  permuted[0] = (s->v0[2] >> 32) | (s->v0[2] << 32);
  permuted[1] = (s->v0[3] >> 32) | (s->v0[3] << 32);
  permuted[2] = (s->v0[0] >> 32) | (s->v0[0] << 32);
  permuted[3] = (s->v0[1] >> 32) | (s->v0[1] << 32);
  hh_update_lanes(s, permuted);
}

static void modular_reduction(uint64_t a3_unmasked, uint64_t a2, uint64_t a1,
                              uint64_t a0, uint64_t* m1, uint64_t* m0) {
  uint64_t a3 = a3_unmasked & 0x3fffffffffffffffull;
  *m1 = a1 ^ ((a3 << 1) | (a2 >> 63)) ^ ((a3 << 2) | (a2 >> 62));
  *m0 = a0 ^ (a2 << 1) ^ (a2 << 2);
}

static uint64_t hh_finalize64(HHState* s) {
  for (int i = 0; i < 4; ++i) permute_and_update(s);
  return s->v0[0] + s->v1[0] + s->mul0[0] + s->mul1[0];
}

static void hh_finalize256(HHState* s, uint64_t hash[4]) {
  for (int i = 0; i < 10; ++i) permute_and_update(s);
  modular_reduction(s->v1[1] + s->mul1[1], s->v1[0] + s->mul1[0],
                    s->v0[1] + s->mul0[1], s->v0[0] + s->mul0[0],
                    &hash[1], &hash[0]);
  modular_reduction(s->v1[3] + s->mul1[3], s->v1[2] + s->mul1[2],
                    s->v0[3] + s->mul0[3], s->v0[2] + s->mul0[2],
                    &hash[3], &hash[2]);
}

#if MT_HH_X86
/* AVX2 bulk packet loop: the 4 u64 hash lanes are one ymm register per
 * state variable.  The zipper-merge is a byte permutation that never
 * crosses the 128-bit pair boundary, so it is a single in-lane
 * VPSHUFB; the 32x32->64 multiplies map to VPMULUDQ exactly
 * ((v & 0xffffffff) * (w >> 32)).  ~8 vector ops per 32-byte packet vs
 * ~50 scalar ops — the host-native analog of the reference dep's AVX2
 * assembly (minio/highwayhash, cmd/bitrot.go:30). */
__attribute__((target("avx2")))
static void hh_update_many_avx2(HHState* s, const uint8_t* data,
                                size_t packets) {
  __m256i v0 = _mm256_loadu_si256((const __m256i*)s->v0);
  __m256i v1 = _mm256_loadu_si256((const __m256i*)s->v1);
  __m256i m0 = _mm256_loadu_si256((const __m256i*)s->mul0);
  __m256i m1 = _mm256_loadu_si256((const __m256i*)s->mul1);
  const __m256i ZIP = _mm256_setr_epi8(
      3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7,
      3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7);
  for (size_t i = 0; i < packets; ++i, data += 32) {
    __m256i lanes = _mm256_loadu_si256((const __m256i*)data);
    v1 = _mm256_add_epi64(v1, _mm256_add_epi64(m0, lanes));
    m0 = _mm256_xor_si256(
        m0, _mm256_mul_epu32(v1, _mm256_srli_epi64(v0, 32)));
    v0 = _mm256_add_epi64(v0, m1);
    m1 = _mm256_xor_si256(
        m1, _mm256_mul_epu32(v0, _mm256_srli_epi64(v1, 32)));
    v0 = _mm256_add_epi64(v0, _mm256_shuffle_epi8(v1, ZIP));
    v1 = _mm256_add_epi64(v1, _mm256_shuffle_epi8(v0, ZIP));
  }
  _mm256_storeu_si256((__m256i*)s->v0, v0);
  _mm256_storeu_si256((__m256i*)s->v1, v1);
  _mm256_storeu_si256((__m256i*)s->mul0, m0);
  _mm256_storeu_si256((__m256i*)s->mul1, m1);
}

static int hh_have_avx2(void) {
  /* relaxed atomics: the lazy `static int have = -1; if (have < 0)`
     formulation is a C data race (ThreadSanitizer tier caught it —
     concurrent first calls from the GIL-released drive fan-out);
     the value is idempotent, so racing initializers are fine as long
     as the accesses themselves are atomic */
  static int have = -1;
  int v = __atomic_load_n(&have, __ATOMIC_RELAXED);
  if (v < 0) {
    v = __builtin_cpu_supports("avx2") ? 1 : 0;
    __atomic_store_n(&have, v, __ATOMIC_RELAXED);
  }
  return v;
}
#endif

static void hh_update_many(HHState* s, const uint8_t* data,
                           size_t packets) {
#if MT_HH_X86
  if (hh_have_avx2()) {
    hh_update_many_avx2(s, data, packets);
    return;
  }
#endif
  for (size_t i = 0; i < packets; ++i) hh_update_packet(s, data + 32 * i);
}

static void hh_process_all(HHState* s, const uint64_t key[4],
                           const uint8_t* data, size_t size) {
  hh_reset(s, key);
  hh_update_many(s, data, size / 32);
  if ((size & 31) != 0)
    hh_update_remainder(s, data + (size & ~(size_t)31), size & 31);
}

/* ---- exported API (ctypes) ---- */

void mt_hh256(const uint64_t key[4], const uint8_t* data, size_t size,
              uint8_t out[32]) {
  HHState s;
  uint64_t hash[4];
  hh_process_all(&s, key, data, size);
  hh_finalize256(&s, hash);
  memcpy(out, hash, 32);
}

uint64_t mt_hh64(const uint64_t key[4], const uint8_t* data, size_t size) {
  HHState s;
  hh_process_all(&s, key, data, size);
  return hh_finalize64(&s);
}

/* Hash `count` consecutive blocks of `block_size` bytes (last one may be
 * short: total = size): the per-shard-block bitrot sweep in one call. */
void mt_hh256_blocks(const uint64_t key[4], const uint8_t* data, size_t size,
                     size_t block_size, uint8_t* out /* count*32 */) {
  size_t off = 0;
  while (off < size) {
    size_t n = size - off < block_size ? size - off : block_size;
    mt_hh256(key, data + off, n, out);
    off += n;
    out += 32;
  }
}

/* Fill the digest slots of an ALREADY-framed buffer in place: `framed`
 * is a sequence of [32-byte digest][<=block_size payload] frames (the
 * layout of cmd/bitrot-streaming.go:46-58).  The caller lays shard and
 * parity bytes directly into the frame payloads (zero-copy PUT
 * pipeline); this pass computes each payload's HighwayHash-256 into
 * its 32-byte header.  GIL-free via ctypes. */
void mt_hh256_fill(const uint64_t key[4], uint8_t* framed, size_t size,
                   size_t block_size) {
  size_t off = 0;
  while (off + 32 < size) {
    size_t n = size - off - 32 < block_size ? size - off - 32 : block_size;
    mt_hh256(key, framed + off + 32, n, framed + off);
    off += 32 + n;
  }
}

/* One-pass framed-shard VERIFY (the GET-side dual of mt_hh256_fill,
 * cmd/bitrot-streaming.go:92-158 ReadAt verification): recompute every
 * block digest and compare.  Returns 0 when all blocks verify, else
 * the 1-based index of the first corrupt block.  GIL-free, no copies —
 * the caller extracts payloads with one strided pass afterwards. */
int mt_hh256_verify_framed(const uint64_t key[4], const uint8_t* framed,
                           size_t size, size_t block_size) {
  size_t off = 0;
  int idx = 1;
  uint8_t digest[32];
  while (off + 32 < size) {
    size_t n = size - off - 32 < block_size ? size - off - 32 : block_size;
    mt_hh256(key, framed + off + 32, n, digest);
    for (int i = 0; i < 32; i++)
      if (digest[i] != framed[off + i]) return idx;
    off += 32 + n;
    idx++;
  }
  return 0;
}

/* One-pass bitrot shard framing (cmd/bitrot-streaming.go:46-58): emit
 * hash || block for every block_size block.  Doing hash + copy in one
 * GIL-free call is what lets concurrent PUT threads scale on the host
 * path.  `out` must hold size + ceil(size/block_size)*32 bytes. */
void mt_hh256_frame(const uint64_t key[4], const uint8_t* data, size_t size,
                    size_t block_size, uint8_t* out) {
  size_t off = 0;
  while (off < size) {
    size_t n = size - off < block_size ? size - off : block_size;
    mt_hh256(key, data + off, n, out);
    memcpy(out + 32, data + off, n);
    off += n;
    out += 32 + n;
  }
}

/* streaming (whole-file bitrot): caller allocates an opaque state buffer */
typedef struct {
  HHState s;
  uint64_t key[4];
  uint8_t buf[32];
  size_t buf_len;
} HHStream;

size_t mt_hh_stream_size(void) { return sizeof(HHStream); }

void mt_hh_stream_init(HHStream* st, const uint64_t key[4]) {
  memcpy(st->key, key, 32);
  hh_reset(&st->s, key);
  st->buf_len = 0;
}

void mt_hh_stream_update(HHStream* st, const uint8_t* data, size_t size) {
  if (st->buf_len) {
    size_t need = 32 - st->buf_len;
    size_t take = size < need ? size : need;
    memcpy(st->buf + st->buf_len, data, take);
    st->buf_len += take;
    data += take;
    size -= take;
    if (st->buf_len == 32 && size > 0) {
      /* only flush when more data follows: a trailing exactly-full buffer
       * must go through Update, not Remainder -- flush lazily */
      hh_update_packet(&st->s, st->buf);
      st->buf_len = 0;
    }
  }
  if (size == 0) return;
  if (st->buf_len == 32) { /* buffered packet + new data: flush it */
    hh_update_packet(&st->s, st->buf);
    st->buf_len = 0;
  }
  if (size > 32) { /* keep >=1 byte (or exactly 32) for the tail */
    size_t packets = (size - 1) / 32;
    hh_update_many(&st->s, data, packets);
    data += packets * 32;
    size -= packets * 32;
  }
  memcpy(st->buf, data, size);
  st->buf_len = size;
}

void mt_hh_stream_final256(HHStream* st, uint8_t out[32]) {
  uint64_t hash[4];
  if (st->buf_len == 32) {
    hh_update_packet(&st->s, st->buf);
  } else if (st->buf_len) {
    hh_update_remainder(&st->s, st->buf, st->buf_len);
  }
  hh_finalize256(&st->s, hash);
  memcpy(out, hash, 32);
  /* leave state reusable via init */
}

/* ---- SipHash-2-4 (object->erasure-set distribution, cmd/erasure-sets.go:629)
 * Standard algorithm; validated against the SipHash paper vectors. */

#define SIP_ROTL(x, b) (uint64_t)(((x) << (b)) | ((x) >> (64 - (b))))
#define SIP_ROUND(v0, v1, v2, v3) \
  do {                            \
    v0 += v1; v1 = SIP_ROTL(v1, 13); v1 ^= v0; v0 = SIP_ROTL(v0, 32); \
    v2 += v3; v3 = SIP_ROTL(v3, 16); v3 ^= v2;                        \
    v0 += v3; v3 = SIP_ROTL(v3, 21); v3 ^= v0;                        \
    v2 += v1; v1 = SIP_ROTL(v1, 17); v1 ^= v2; v2 = SIP_ROTL(v2, 32); \
  } while (0)

uint64_t mt_siphash24(uint64_t k0, uint64_t k1, const uint8_t* data,
                      size_t size) {
  uint64_t v0 = 0x736f6d6570736575ull ^ k0;
  uint64_t v1 = 0x646f72616e646f6dull ^ k1;
  uint64_t v2 = 0x6c7967656e657261ull ^ k0;
  uint64_t v3 = 0x7465646279746573ull ^ k1;
  const size_t end = size - (size % 8);
  size_t i;
  for (i = 0; i < end; i += 8) {
    uint64_t m = read_le64(data + i);
    v3 ^= m;
    SIP_ROUND(v0, v1, v2, v3);
    SIP_ROUND(v0, v1, v2, v3);
    v0 ^= m;
  }
  uint64_t b = ((uint64_t)size) << 56;
  for (i = 0; i < size % 8; ++i) b |= ((uint64_t)data[end + i]) << (8 * i);
  v3 ^= b;
  SIP_ROUND(v0, v1, v2, v3);
  SIP_ROUND(v0, v1, v2, v3);
  v0 ^= b;
  v2 ^= 0xff;
  SIP_ROUND(v0, v1, v2, v3);
  SIP_ROUND(v0, v1, v2, v3);
  SIP_ROUND(v0, v1, v2, v3);
  SIP_ROUND(v0, v1, v2, v3);
  return v0 ^ v1 ^ v2 ^ v3;
}
