"""minio_tpu — a TPU-native object-storage framework.

A from-scratch re-design of MinIO's capabilities (S3 API, erasure-coded
distributed object store, healing, bitrot protection) with the compute hot
path — GF(2^8) Reed-Solomon coding and hash verification — executed as
batched JAX/XLA kernels on TPU, and host orchestration in Python/C++.

Reference behavior map: /root/repo/SURVEY.md (citations into zonshy/minio).
"""

__version__ = "0.1.0"
