"""Server bootstrap + CLI (cmd/server-main.go:389 serverMain, L0).

``python -m minio_tpu server /data1 /data2 ...`` boots a single-node
server: drive init + format, set sizing, object layer assembly, IAM load,
S3 + admin frontend.  Distributed deployments assemble via
minio_tpu.cluster (each host lists every node's drives in the same
order, as the reference does with ellipses endpoints).
"""

from __future__ import annotations

import argparse
import os
import sys

from .objectlayer.sets import ErasureSets
from .s3.server import S3Server

# set sizing (cmd/endpoint-ellipses.go:44 setSizes{4..16})
SET_SIZES = list(range(16, 3, -1))


def choose_set_drive_count(n: int, override: int | None = None) -> int:
    """Largest valid set size dividing the drive count (getSetIndexes,
    cmd/endpoint-ellipses.go:132); small counts (1-3) form one set."""
    if override:
        if n % override != 0:
            raise ValueError(f"drive count {n} not divisible by "
                             f"set size {override}")
        return override
    if n < 4:
        return n
    for size in SET_SIZES:
        if n % size == 0:
            return size
    raise ValueError(f"no valid erasure set size for {n} drives "
                     f"(need a divisor in 4..16)")


def build_server(dirs: list[str], address: str = "127.0.0.1:9000",
                 access_key: str | None = None,
                 secret_key: str | None = None,
                 set_drive_count: int | None = None,
                 backend: str = "auto", block_size: int | None = None,
                 region: str = "us-east-1") -> S3Server:
    access_key = access_key or os.environ.get("MT_ROOT_USER", "minioadmin")
    secret_key = secret_key or os.environ.get("MT_ROOT_PASSWORD",
                                              "minioadmin")
    for d in dirs:
        os.makedirs(d, exist_ok=True)
    sdc = choose_set_drive_count(len(dirs),
                                 set_drive_count or
                                 int(os.environ.get(
                                     "MT_ERASURE_SET_DRIVE_COUNT", 0))
                                 or None)
    kwargs = {"backend": backend}
    if block_size:
        kwargs["block_size"] = block_size
    layer = ErasureSets.from_dirs(dirs, len(dirs) // sdc, sdc, **kwargs)
    layer.start_drive_monitor()
    host, _, port = address.rpartition(":")
    srv = S3Server(layer, access_key=access_key, secret_key=secret_key,
                   region=region, host=host or "0.0.0.0", port=int(port))
    srv.iam.load()
    # background services whose lifecycle follows the server's
    # (cmd/server-main.go initDataCrawler + initBackgroundHealing):
    # the data crawler feeds usage metrics/ILM, the heal sweep repairs
    # drift; both intervals are env-tunable and the crawler shares the
    # server's update tracker so listings invalidate on writes
    from .background.crawler import Crawler
    from .background.heal import BackgroundHealer
    from .background.tracker import DataUpdateTracker
    from .objectlayer.tiering import transition_fn
    tracker = DataUpdateTracker()
    srv.attach_tracker(tracker)
    crawler = Crawler(
        layer, bucket_meta=srv.bucket_meta,
        interval_s=float(os.environ.get("MT_CRAWL_INTERVAL_S", "60")),
        transition_fn=transition_fn(srv.transition), tracker=tracker)
    healer = BackgroundHealer(
        layer,
        interval_s=float(os.environ.get("MT_HEAL_INTERVAL_S", "3600")),
        deep_every=int(os.environ.get("MT_HEAL_DEEP_EVERY", "8")))
    srv.healer = healer            # mt_heal_* metrics + admin heal state
    srv.crawler = crawler
    srv.attach_background(crawler, healer)
    return srv


def build_gateway_server(kind: str, target: str,
                         address: str = "127.0.0.1:9000",
                         access_key: str | None = None,
                         secret_key: str | None = None,
                         cache_dirs: list[str] | None = None,
                         region: str = "us-east-1") -> S3Server:
    """`minio gateway <kind>` analog (cmd/gateway-main.go): the same S3
    frontend over a foreign backend, optionally fronted by the disk
    cache (cmd/disk-cache.go:88 deploys cacheObjects for gateways)."""
    from . import gateway as gw

    access_key = access_key or os.environ.get("MT_ROOT_USER", "minioadmin")
    secret_key = secret_key or os.environ.get("MT_ROOT_PASSWORD",
                                              "minioadmin")
    cls = gw.lookup(kind)
    if kind == "s3":
        g = cls(target,
                os.environ.get("MT_GATEWAY_ACCESS_KEY", access_key),
                os.environ.get("MT_GATEWAY_SECRET_KEY", secret_key),
                region)
    else:
        g = cls(target)
    layer = g.new_gateway_layer()
    if cache_dirs:
        from .objectlayer.diskcache import CacheObjects
        layer = CacheObjects(layer, cache_dirs)
    host, _, port = address.rpartition(":")
    srv = S3Server(layer, access_key=access_key, secret_key=secret_key,
                   region=region, host=host or "0.0.0.0", port=int(port))
    srv.iam.load()
    return srv


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="minio_tpu", description="TPU-native S3 object storage server")
    sub = parser.add_subparsers(dest="command", required=True)
    pg = sub.add_parser("gateway", help="serve S3 over a foreign backend")
    pg.add_argument("kind", help="nas | s3 | azure | gcs | hdfs")
    pg.add_argument("target", help="mount path (nas) or endpoint URL (s3)")
    pg.add_argument("--address", default="0.0.0.0:9000")
    pg.add_argument("--access-key", default=None)
    pg.add_argument("--secret-key", default=None)
    pg.add_argument("--cache-dir", action="append", default=None,
                    help="disk cache drive (repeatable)")
    pg.add_argument("--region", default="us-east-1")
    pn = sub.add_parser("node", help="start one distributed cluster node")
    pn.add_argument("--node-id", required=True)
    pn.add_argument("--secret", default=None,
                    help="internode RPC secret (MT_CLUSTER_SECRET)")
    pn.add_argument("--address", default="127.0.0.1:0",
                    help="S3 frontend address")
    pn.add_argument("--set-drive-count", type=int, default=None)
    pn.add_argument("--backend", default="auto",
                    choices=["auto", "tpu", "numpy"])
    pn.add_argument("peers", nargs="+",
                    help="topology: id=host:rpcport=dir1,dir2 per node, "
                         "SAME order on every node")
    ps = sub.add_parser("server", help="start the object storage server")
    ps.add_argument("dirs", nargs="+", help="drive directories")
    ps.add_argument("--address", default="0.0.0.0:9000")
    ps.add_argument("--access-key", default=None)
    ps.add_argument("--secret-key", default=None)
    ps.add_argument("--set-drive-count", type=int, default=None)
    ps.add_argument("--backend", default="auto",
                    choices=["auto", "tpu", "numpy"],
                    help="erasure compute backend")
    ps.add_argument("--block-size", type=int, default=None)
    ps.add_argument("--region", default="us-east-1")
    args = parser.parse_args(argv)

    if args.command == "node":
        from .cluster import NodeSpec, run_node
        secret = args.secret or os.environ.get("MT_CLUSTER_SECRET", "")
        specs = []
        for p in args.peers:
            nid, endpoint, dirs = p.split("=", 2)
            drive_dirs = [d for d in dirs.split(",") if d]
            if nid == args.node_id:
                for d in drive_dirs:
                    os.makedirs(d, exist_ok=True)
            specs.append(NodeSpec(nid, drive_dirs,
                                  endpoint=f"http://{endpoint}"))
        if not secret:
            # the RPC plane grants full shard read/write: a well-known
            # default secret is acceptable only on loopback topologies
            if any(not s.endpoint.startswith(("http://127.", "http://localhost"))
                   for s in specs):
                parser.error("distributed nodes require --secret or "
                             "MT_CLUSTER_SECRET (refusing a default "
                             "secret on non-loopback endpoints)")
            secret = "cluster-secret"
        node, srv = run_node(args.node_id, specs, secret, args.address,
                             args.set_drive_count, backend=args.backend)
        shost = args.address.rpartition(":")[0] or "127.0.0.1"
        print(f"minio-tpu node {args.node_id}: rpc={node.rpc.endpoint} "
              f"s3=http://{shost}:{srv.port}", flush=True)
        try:
            srv.shutdown.wait()       # admin stop or Ctrl-C ends the node
        except KeyboardInterrupt:
            srv.stop()
        node.stop()
        return 0

    if args.command == "gateway":
        srv = build_gateway_server(args.kind, args.target, args.address,
                                   args.access_key, args.secret_key,
                                   args.cache_dir, args.region)
        print(f"minio-tpu gateway [{args.kind}] -> {args.target}",
              flush=True)
        print(f"S3 endpoint: http://{args.address}", flush=True)
        try:
            srv.httpd.serve_forever()
        except KeyboardInterrupt:
            srv.stop()
        return 0

    srv = build_server(args.dirs, args.address, args.access_key,
                       args.secret_key, args.set_drive_count,
                       args.backend, args.block_size, args.region)
    n = len(args.dirs)
    sdc = srv.layer.set_drive_count
    print(f"minio-tpu server: {n} drives, "
          f"{n // sdc} set(s) x {sdc} drives, "
          f"backend={args.backend}", flush=True)
    print(f"S3 endpoint: http://{args.address}", flush=True)
    print(f"admin:       http://{args.address}/minio-tpu/admin/v1/info",
          flush=True)
    print(f"metrics:     http://{args.address}/minio-tpu/metrics",
          flush=True)
    try:
        srv.httpd.serve_forever()
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
