"""S3 Select — SQL over CSV/JSON objects (pkg/s3select).

Reference: `pkg/s3select/select.go:541` (NewS3Select), `:398` (Evaluate
record loop), SQL engine under `pkg/s3select/sql/` (participle parser,
aggregation, functions), response framing `pkg/s3select/message.go`.

This package is the TPU build's equivalent: a hand-written SQL
lexer/parser/evaluator (`sql.py`), CSV/JSON record readers (`records.py`),
and AWS event-stream response framing (`message.py`).  `run_select` glues
them: parse the SelectObjectContentRequest XML, stream records through
the compiled query, frame the output.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

from ..utils import close_quietly
from . import message, records, sql


class SelectError(Exception):
    """Carries an S3 error code for the handler."""

    def __init__(self, code: str, msg: str = ""):
        super().__init__(msg or code)
        self.code = code


def _text(el, name: str, default: str = "") -> str:
    if el is None:
        return default
    v = el.findtext(name)
    return default if v is None else v


class SelectRequest:
    """Parsed SelectObjectContentRequest (pkg/s3select/select.go:114)."""

    def __init__(self, expression: str, input_format: str,
                 input_opts: dict, output_format: str, output_opts: dict,
                 compression: str, progress: bool = False):
        self.expression = expression
        self.input_format = input_format      # "CSV" | "JSON"
        self.input_opts = input_opts
        self.output_format = output_format    # "CSV" | "JSON"
        self.output_opts = output_opts
        self.compression = compression    # "NONE" | "GZIP" | "BZIP2"
        # RequestProgress.Enabled (select.go:114 parseRequestProgress):
        # periodic Progress frames ride the stream only when asked
        self.progress = progress

    @classmethod
    def parse(cls, payload: bytes) -> "SelectRequest":
        try:
            root = ET.fromstring(payload)
        except ET.ParseError as e:
            raise SelectError("MalformedXML") from e
        # strip namespaces
        for el in root.iter():
            if "}" in el.tag:
                el.tag = el.tag.split("}", 1)[1]
        expr = root.findtext("Expression") or ""
        etype = root.findtext("ExpressionType") or "SQL"
        if etype.upper() != "SQL" or not expr.strip():
            raise SelectError("InvalidExpressionType")
        inser = root.find("InputSerialization")
        if inser is None:
            raise SelectError("InvalidRequestParameter",
                              "InputSerialization required")
        compression = _text(inser, "CompressionType", "NONE").upper()
        if compression not in ("NONE", "GZIP", "BZIP2"):
            raise SelectError("InvalidCompressionFormat")
        csv_el, json_el = inser.find("CSV"), inser.find("JSON")
        parquet_el = inser.find("Parquet")
        if parquet_el is not None:
            # parquet pages carry their own codec; object-level
            # compression is invalid (select.go parquet input rules)
            if compression != "NONE":
                raise SelectError("InvalidCompressionFormat")
            fmt, opts = "PARQUET", {}
        elif csv_el is not None:
            fmt = "CSV"
            opts = {
                "header": _text(csv_el, "FileHeaderInfo", "NONE").upper(),
                "field_delim": _text(csv_el, "FieldDelimiter", ","),
                "record_delim": _text(csv_el, "RecordDelimiter", "\n"),
                "quote": _text(csv_el, "QuoteCharacter", '"'),
                "comment": _text(csv_el, "Comments", ""),
            }
        elif json_el is not None:
            fmt = "JSON"
            opts = {"type": _text(json_el, "Type", "LINES").upper()}
        else:
            raise SelectError("InvalidDataSource")
        outser = root.find("OutputSerialization")
        ocsv = outser.find("CSV") if outser is not None else None
        ojson = outser.find("JSON") if outser is not None else None
        if ojson is not None:
            ofmt, oopts = "JSON", {
                "record_delim": _text(ojson, "RecordDelimiter", "\n")}
        else:
            ofmt, oopts = "CSV", {
                "field_delim": _text(ocsv, "FieldDelimiter", ","),
                "record_delim": _text(ocsv, "RecordDelimiter", "\n"),
                "quote": _text(ocsv, "QuoteCharacter", '"'),
            }
        prog = root.find("RequestProgress")
        progress = _text(prog, "Enabled", "FALSE").upper() == "TRUE"
        return cls(expr, fmt, opts, ofmt, oopts, compression, progress)


def _fast_filter_params(query) -> tuple[str, str, object] | None:
    """(field, op, literal) for the native NDJSON prefilter, or None
    when the WHERE isn't the simple comparison shape the C scanner
    handles (native/jsonscan.cc)."""
    w = query.where
    if not isinstance(w, sql.Binary) or w.op not in records._OPS:
        return None
    col, lit, op = None, None, w.op
    if isinstance(w.left, sql.Column) and isinstance(w.right, sql.Literal):
        col, lit = w.left, w.right
    elif isinstance(w.left, sql.Literal) and isinstance(w.right,
                                                       sql.Column):
        col, lit = w.right, w.left
        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
    else:
        return None
    path = list(col.path)
    if path and path[0] == query.table_alias:
        path = path[1:]
    if len(path) != 1:                 # nested fields: full reader
        return None
    import re as _re
    if _re.fullmatch(r"_\d+", path[0]):
        return None                    # positional column: evaluator
                                       # resolves by index, not by key
    return path[0], op, lit.value


class _ScanState:
    """Live byte counters the pipeline wrappers tick as blocks flow —
    the source of Progress/Stats numbers."""

    __slots__ = ("scanned", "processed", "returned")

    def __init__(self):
        self.scanned = 0      # stored (possibly compressed) bytes read
        self.processed = 0    # bytes after object-level decompression
        self.returned = 0     # output payload bytes emitted


def _counted(chunks, st: _ScanState, attr: str):
    """Pass chunks through, adding their lengths to one counter."""
    try:
        for c in chunks:
            setattr(st, attr, getattr(st, attr) + len(c))
            yield c
    finally:
        close_quietly(chunks)


def _gunzip_chunks(chunks):
    """Streaming multi-member gzip decode; empty/truncated/corrupt
    input raises SelectError exactly where ``gzip.decompress`` would
    have (the buffered reference semantics)."""
    import zlib
    d = zlib.decompressobj(31)
    fed = False                 # current member has received bytes
    members = 0
    try:
        for c in chunks:
            data = c
            while data:
                try:
                    out = d.decompress(data)
                except zlib.error as e:
                    raise SelectError("InvalidCompressionFormat") from e
                fed = True
                if out:
                    yield out
                if d.eof:
                    members += 1
                    data = d.unused_data
                    d = zlib.decompressobj(31)
                    fed = False
                else:
                    data = b""
        if members == 0 or fed:
            # no complete stream at all, or one ended mid-member —
            # gzip.decompress raises EOFError for both
            raise SelectError("InvalidCompressionFormat")
    finally:
        close_quietly(chunks)


def _bunzip_chunks(chunks):
    """Streaming (possibly concatenated) bzip2 decode, matching
    ``bz2.decompress``: empty input is empty output, garbage AFTER a
    complete stream is ignored, a stream ending mid-member errors."""
    import bz2
    d = bz2.BZ2Decompressor()
    fed = False
    members = 0
    try:
        for c in chunks:
            data = c
            while data:
                try:
                    out = d.decompress(data)
                except (OSError, ValueError, EOFError) as e:
                    if members:
                        return      # trailing garbage: ignored
                    raise SelectError("InvalidCompressionFormat") from e
                fed = True
                if out:
                    yield out
                if d.eof:
                    members += 1
                    data = d.unused_data
                    d = bz2.BZ2Decompressor()
                    fed = False
                else:
                    data = b""
        if fed:
            raise SelectError("InvalidCompressionFormat")
    finally:
        close_quietly(chunks)


def _json_lines_rows(block: bytes, opts: dict, fastp):
    """Rows of one JSON-Lines block: the C prefilter keeps candidate
    lines when the WHERE fits its shape (the full WHERE still runs on
    survivors downstream, so semantics are unchanged); otherwise every
    line parses."""
    if fastp is not None:
        spans = records.ndjson_prefilter(block, *fastp)
        if spans is not None:
            for lo, hi in spans:
                line = block[lo:hi].strip()
                if line:
                    yield records._wrap(records._json.loads(
                        line.decode("utf-8", errors="replace")))
            return
    yield from records.json_records(block, opts)


def _rechunk(chunks, n: int):
    """Split oversized pieces so downstream blocks stay <= n bytes —
    a non-streaming layer (the ObjectLayer default reader yields the
    whole object as one chunk) must not defeat the record splitter's
    memory bound."""
    try:
        for c in chunks:
            if len(c) <= n:
                yield c
            else:
                for off in range(0, len(c), n):
                    yield bytes(c[off:off + n])
    finally:
        close_quietly(chunks)


def _record_reader(req: SelectRequest, query, blocks):
    """One continuous record stream over complete-record blocks —
    what sql.execute consumes."""
    if req.input_format == "CSV":
        yield from records.csv_records_stream(blocks, req.input_opts)
        return
    fastp = _fast_filter_params(query)
    for block in blocks:
        yield from _json_lines_rows(block, req.input_opts, fastp)


# Records event payload cap (message.go maxRecordSize): the buffered
# reference chunked its whole output at these boundaries, and the
# incremental framer reproduces them exactly — byte-identical streams
RECORDS_CHUNK = 1 << 20
# scanned-byte interval between periodic Progress frames (when the
# request asked); each is preceded by a Cont keep-alive frame
PROGRESS_INTERVAL = 8 << 20


def run_select_stream(payload: bytes, chunks, *,
                      block_bytes: int = 1 << 20,
                      on_stats=None):
    """Incremental SelectObjectContentRequest scanner: pulls decoded
    object bytes from ``chunks`` block-at-a-time, feeds record
    splitting and the query, and yields framed events as the scan
    advances — peak memory O(block) regardless of object size
    (select.go:398 Evaluate record loop).

    Request/SQL parse errors raise :exc:`SelectError` eagerly, before
    the first frame; reader errors surface as SelectError from the
    generator mid-iteration (the handler turns them into a 400 when
    nothing was sent yet, an error frame when the stream is live).
    ``on_stats(scanned, processed, returned)`` fires before the Stats
    frame.  JSON DOCUMENT and Parquet inputs need random access /
    whole-value parses and fall back to materializing the object."""
    req = SelectRequest.parse(payload)
    try:
        query = sql.parse_query(req.expression)
    except sql.SQLError as e:
        raise SelectError("ParseSelectFailure", str(e)) from e
    return _frames(req, query, chunks, block_bytes, on_stats)


def _frames(req: SelectRequest, query, chunks, block_bytes: int,
            on_stats):
    st = _ScanState()
    src = _counted(chunks, st, "scanned")
    if req.compression == "GZIP":
        src = _gunzip_chunks(src)
    elif req.compression == "BZIP2":
        src = _bunzip_chunks(src)
    src = _counted(src, st, "processed")
    if block_bytes > 0:
        src = _rechunk(src, block_bytes)

    if req.input_format == "PARQUET" or (
            req.input_format == "JSON" and
            req.input_opts.get("type", "LINES") != "LINES"):
        # whole-value inputs: Parquet needs footer-first random access,
        # a JSON DOCUMENT is one value — materialize (the documented
        # non-streaming fallback; CSV and JSON Lines stay O(block))
        data = b"".join(src)   # whole-body-ok — the documented materializing fallback (governor charges 2x the decoded estimate, docs/resilience.md)
        if req.input_format == "PARQUET":
            from . import parquet as pq
            try:
                reader = pq.parquet_records(data)
            except pq.ParquetError as e:
                raise SelectError("InvalidDataSource", str(e)) from e
        else:
            reader = records.json_records(data, req.input_opts)
    else:
        quote = None
        delim = b"\n"
        if req.input_format == "CSV":
            delim = (req.input_opts.get("record_delim") or "\n").encode()
            q = req.input_opts.get("quote", '"')
            quote = q.encode() if q else None
        fdelim = (req.input_opts.get("field_delim") or ",").encode() \
            if req.input_format == "CSV" else b","
        blocks = records.record_blocks(src, delim, quote, fdelim)
        reader = _record_reader(req, query, blocks)

    pending = bytearray()
    last_progress = 0
    try:
        try:
            rows = sql.execute(query, reader)
            for row in rows:
                if req.output_format == "JSON":
                    rec = records.to_json_record(row, req.output_opts)
                else:
                    rec = records.to_csv_record(row, req.output_opts)
                pending += rec
                st.returned += len(rec)
                while len(pending) >= RECORDS_CHUNK:
                    yield message.records_event(
                        bytes(pending[:RECORDS_CHUNK]))
                    del pending[:RECORDS_CHUNK]
                if req.progress and \
                        st.scanned - last_progress >= PROGRESS_INTERVAL:
                    last_progress = st.scanned
                    yield message.continuation_event()
                    yield message.progress_event(
                        st.scanned, st.processed, st.returned)
        except sql.SQLError as e:
            raise SelectError("EvaluatorInvalidArguments", str(e)) from e
        except (ValueError, TypeError, KeyError) as e:
            # reader parse failures surface mid-iteration (generators):
            # malformed input is a 400 parse error, never a 500
            code = {"JSON": "JSONParsingError",
                    "PARQUET": "InvalidDataSource"}.get(
                req.input_format, "CSVParsingError")
            raise SelectError(code, str(e)) from e
        if pending:
            yield message.records_event(bytes(pending))
        if req.progress:
            yield message.progress_event(st.scanned, st.processed,
                                         st.returned)
        if on_stats is not None:
            on_stats(st.scanned, st.processed, st.returned)
        yield message.stats_event(st.scanned, st.processed, st.returned)
        yield message.end_event()
    finally:
        close_quietly(src)


def run_select(payload: bytes, data: bytes) -> bytes:
    """Execute a SelectObjectContentRequest against object bytes;
    returns the framed event-stream response body.  One join over the
    incremental scanner — the whole-buffer path and the streaming path
    ARE the same code, so their outputs are byte-identical by
    construction (pinned anyway by tests/test_select_stream.py).
    block_bytes=0: the single whole-buffer chunk is not re-split."""
    return b"".join(   # whole-body-ok — the whole-buffer compat wrapper IS this join; callers with real streams use run_select_stream
        run_select_stream(payload, (data,), block_bytes=0))
