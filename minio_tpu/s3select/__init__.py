"""S3 Select — SQL over CSV/JSON objects (pkg/s3select).

Reference: `pkg/s3select/select.go:541` (NewS3Select), `:398` (Evaluate
record loop), SQL engine under `pkg/s3select/sql/` (participle parser,
aggregation, functions), response framing `pkg/s3select/message.go`.

This package is the TPU build's equivalent: a hand-written SQL
lexer/parser/evaluator (`sql.py`), CSV/JSON record readers (`records.py`),
and AWS event-stream response framing (`message.py`).  `run_select` glues
them: parse the SelectObjectContentRequest XML, stream records through
the compiled query, frame the output.
"""

from __future__ import annotations

import gzip
import xml.etree.ElementTree as ET

from . import message, records, sql


class SelectError(Exception):
    """Carries an S3 error code for the handler."""

    def __init__(self, code: str, msg: str = ""):
        super().__init__(msg or code)
        self.code = code


def _text(el, name: str, default: str = "") -> str:
    if el is None:
        return default
    v = el.findtext(name)
    return default if v is None else v


class SelectRequest:
    """Parsed SelectObjectContentRequest (pkg/s3select/select.go:114)."""

    def __init__(self, expression: str, input_format: str,
                 input_opts: dict, output_format: str, output_opts: dict,
                 compression: str):
        self.expression = expression
        self.input_format = input_format      # "CSV" | "JSON"
        self.input_opts = input_opts
        self.output_format = output_format    # "CSV" | "JSON"
        self.output_opts = output_opts
        self.compression = compression    # "NONE" | "GZIP" | "BZIP2"

    @classmethod
    def parse(cls, payload: bytes) -> "SelectRequest":
        try:
            root = ET.fromstring(payload)
        except ET.ParseError as e:
            raise SelectError("MalformedXML") from e
        # strip namespaces
        for el in root.iter():
            if "}" in el.tag:
                el.tag = el.tag.split("}", 1)[1]
        expr = root.findtext("Expression") or ""
        etype = root.findtext("ExpressionType") or "SQL"
        if etype.upper() != "SQL" or not expr.strip():
            raise SelectError("InvalidExpressionType")
        inser = root.find("InputSerialization")
        if inser is None:
            raise SelectError("InvalidRequestParameter",
                              "InputSerialization required")
        compression = _text(inser, "CompressionType", "NONE").upper()
        if compression not in ("NONE", "GZIP", "BZIP2"):
            raise SelectError("InvalidCompressionFormat")
        csv_el, json_el = inser.find("CSV"), inser.find("JSON")
        parquet_el = inser.find("Parquet")
        if parquet_el is not None:
            # parquet pages carry their own codec; object-level
            # compression is invalid (select.go parquet input rules)
            if compression != "NONE":
                raise SelectError("InvalidCompressionFormat")
            fmt, opts = "PARQUET", {}
        elif csv_el is not None:
            fmt = "CSV"
            opts = {
                "header": _text(csv_el, "FileHeaderInfo", "NONE").upper(),
                "field_delim": _text(csv_el, "FieldDelimiter", ","),
                "record_delim": _text(csv_el, "RecordDelimiter", "\n"),
                "quote": _text(csv_el, "QuoteCharacter", '"'),
                "comment": _text(csv_el, "Comments", ""),
            }
        elif json_el is not None:
            fmt = "JSON"
            opts = {"type": _text(json_el, "Type", "LINES").upper()}
        else:
            raise SelectError("InvalidDataSource")
        outser = root.find("OutputSerialization")
        ocsv = outser.find("CSV") if outser is not None else None
        ojson = outser.find("JSON") if outser is not None else None
        if ojson is not None:
            ofmt, oopts = "JSON", {
                "record_delim": _text(ojson, "RecordDelimiter", "\n")}
        else:
            ofmt, oopts = "CSV", {
                "field_delim": _text(ocsv, "FieldDelimiter", ","),
                "record_delim": _text(ocsv, "RecordDelimiter", "\n"),
                "quote": _text(ocsv, "QuoteCharacter", '"'),
            }
        return cls(expr, fmt, opts, ofmt, oopts, compression)


def _try_json_fast_path(query, data: bytes, input_opts: dict):
    """Reader over only the rows the C scanner kept, or None when the
    WHERE isn't the simple comparison shape the scanner handles."""
    w = query.where
    if not isinstance(w, sql.Binary) or w.op not in records._OPS:
        return None
    col, lit, op = None, None, w.op
    if isinstance(w.left, sql.Column) and isinstance(w.right, sql.Literal):
        col, lit = w.left, w.right
    elif isinstance(w.left, sql.Literal) and isinstance(w.right,
                                                       sql.Column):
        col, lit = w.right, w.left
        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
    else:
        return None
    path = list(col.path)
    if path and path[0] == query.table_alias:
        path = path[1:]
    if len(path) != 1:                 # nested fields: full reader
        return None
    import re as _re
    if _re.fullmatch(r"_\d+", path[0]):
        return None                    # positional column: evaluator
                                       # resolves by index, not by key
    spans = records.ndjson_prefilter(data, path[0], op, lit.value)
    if spans is None:
        return None

    def rows():
        for lo, hi in spans:
            line = data[lo:hi].strip()
            if line:
                yield records._wrap(records._json.loads(
                    line.decode("utf-8", errors="replace")))
    return rows()


def run_select(payload: bytes, data: bytes) -> bytes:
    """Execute a SelectObjectContentRequest against object bytes; returns
    the framed event-stream response body."""
    req = SelectRequest.parse(payload)
    bytes_scanned = len(data)        # compressed bytes read from storage
    if req.compression == "GZIP":
        try:
            data = gzip.decompress(data)
        except (OSError, EOFError) as e:   # EOFError: truncated stream
            raise SelectError("InvalidCompressionFormat") from e
    elif req.compression == "BZIP2":
        # pkg/s3select/select.go:310 accepts bzip2Type the same way
        import bz2
        try:
            data = bz2.decompress(data)
        except (OSError, ValueError, EOFError) as e:
            raise SelectError("InvalidCompressionFormat") from e
    try:
        query = sql.parse_query(req.expression)
    except sql.SQLError as e:
        raise SelectError("ParseSelectFailure", str(e)) from e
    if req.input_format == "CSV":
        reader = records.csv_records(data, req.input_opts)
    elif req.input_format == "PARQUET":
        from . import parquet as pq
        try:
            reader = pq.parquet_records(data)
        except pq.ParquetError as e:
            raise SelectError("InvalidDataSource", str(e)) from e
    else:
        reader = records.json_records(data, req.input_opts)
        # simdjson-role fast path (native/jsonscan.cc): a WHERE of the
        # form <top-level field> <op> <literal> over JSON LINES scans
        # the raw bytes in C and parses only candidate rows; the full
        # WHERE still runs on survivors, so semantics are unchanged
        if req.input_opts.get("type", "LINES") == "LINES":
            fast = _try_json_fast_path(query, data, req.input_opts)
            if fast is not None:
                reader = fast

    bytes_processed = len(data)      # bytes after decompression
    out_payload = bytearray()
    returned = 0
    try:
        rows = sql.execute(query, reader)
        for row in rows:
            if req.output_format == "JSON":
                rec = records.to_json_record(row, req.output_opts)
            else:
                rec = records.to_csv_record(row, req.output_opts)
            out_payload += rec
            returned += len(rec)
    except sql.SQLError as e:
        raise SelectError("EvaluatorInvalidArguments", str(e)) from e
    except (ValueError, TypeError, KeyError) as e:
        # reader parse failures surface mid-iteration (generators):
        # malformed input is a 400 parse error, never a 500
        code = {"JSON": "JSONParsingError",
                "PARQUET": "InvalidDataSource"}.get(
            req.input_format, "CSVParsingError")
        raise SelectError(code, str(e)) from e

    frames = bytearray()
    # chunk Records payload into <=1 MiB events (message.go maxRecordSize)
    CHUNK = 1 << 20
    for off in range(0, len(out_payload), CHUNK):
        frames += message.records_event(bytes(out_payload[off:off + CHUNK]))
    frames += message.stats_event(bytes_scanned, bytes_processed, returned)
    frames += message.end_event()
    return bytes(frames)
