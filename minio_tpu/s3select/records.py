"""Record readers/writers for S3 Select (pkg/s3select/csv/, json/).

CSV input honors FileHeaderInfo USE/IGNORE/NONE, custom delimiters and
quotes (pkg/s3select/csv/args.go); JSON input handles LINES and DOCUMENT
types (pkg/s3select/json/args.go).  Positional columns are always
available as _1.._N, matching the reference's column addressing.
"""

from __future__ import annotations

import csv as _csv
import io
import json as _json
from typing import Iterator


def csv_records(data: bytes, opts: dict) -> Iterator[dict]:
    text = data.decode("utf-8", errors="replace")
    rd = opts.get("record_delim", "\n")
    if rd not in ("\n", "\r\n"):
        text = text.replace(rd, "\n")
    comment = opts.get("comment") or None
    reader = _csv.reader(
        io.StringIO(text),
        delimiter=opts.get("field_delim", ",") or ",",
        quotechar=opts.get("quote", '"') or '"')
    header_mode = opts.get("header", "NONE")
    headers: list[str] | None = None
    saw_first = False                # first NON-skipped row is the header
    for fields in reader:
        if not fields:
            continue
        if comment and fields[0].startswith(comment):
            continue
        if not saw_first:
            saw_first = True
            if header_mode == "USE":
                headers = [h.strip() for h in fields]
                continue
            if header_mode == "IGNORE":
                continue
        # named keys only when headers exist — SELECT * must not emit
        # columns twice; _N positional addressing is resolved by the SQL
        # evaluator's index fallback
        row: dict = {}
        for j, v in enumerate(fields):
            if headers and j < len(headers):
                row[headers[j]] = v
            else:
                row[f"_{j + 1}"] = v
        yield row


_SCAN_LIB = None
_SCAN_TRIED = False
_OPS = {"=": 0, "!=": 1, "<>": 1, "<": 2, "<=": 3, ">": 4, ">=": 5}


def _scan_lib():
    global _SCAN_LIB, _SCAN_TRIED
    if _SCAN_TRIED:
        return _SCAN_LIB
    import ctypes
    import os as _os

    from ..utils import nativelib
    src = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))), "native",
        "jsonscan.cc")
    so = _os.path.join(_os.path.dirname(src), "build", "libmtjscan.so")
    lib = nativelib.load(src, so)
    if lib is not None:
        lib.mt_ndjson_filter.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t), ctypes.c_long]
        lib.mt_ndjson_filter.restype = ctypes.c_long
    _SCAN_LIB = lib
    _SCAN_TRIED = True
    return lib


def ndjson_prefilter(data: bytes, field: str, op: str,
                     value) -> list[tuple[int, int]] | None:
    """Byte ranges of NDJSON rows that MIGHT satisfy `field op value`
    (native/jsonscan.cc — the simdjson-role scanner): conservative-
    exact, so callers re-evaluate the full WHERE on survivors.  None =
    fast path unavailable (no native lib / unsupported op or type)."""
    import ctypes
    lib = _scan_lib()
    if lib is None or op not in _OPS:
        return None
    if isinstance(value, bool) or value is None:
        return None
    if isinstance(value, (int, float)):
        kind, num, sval = 0, float(value), b""
    elif isinstance(value, str):
        kind, num, sval = 1, 0.0, value.encode()
    else:
        return None
    cap = max(1024, data.count(b"\n") + 2)
    while True:
        out = (ctypes.c_size_t * (2 * cap))()
        got = lib.mt_ndjson_filter(
            data, len(data), field.encode(), len(field.encode()),
            _OPS[op], kind, num, sval, len(sval), out, cap)
        if got >= 0:
            return [(out[2 * i], out[2 * i + 1]) for i in range(got)]
        cap *= 2


def json_records(data: bytes, opts: dict) -> Iterator[dict]:
    jtype = opts.get("type", "LINES")
    text = data.decode("utf-8", errors="replace")
    if jtype == "LINES":
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            yield _wrap(_json.loads(line))
    else:  # DOCUMENT: one value, or concatenated values
        dec = _json.JSONDecoder()
        i, n = 0, len(text)
        while i < n:
            while i < n and text[i].isspace():
                i += 1
            if i >= n:
                break
            obj, end = dec.raw_decode(text, i)
            i = end
            if isinstance(obj, list):
                for item in obj:
                    yield _wrap(item)
            else:
                yield _wrap(obj)


def _wrap(obj) -> dict:
    if isinstance(obj, dict):
        return obj
    return {"_1": obj}


def _fmt(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def to_csv_record(row: dict, opts: dict) -> bytes:
    delim = opts.get("field_delim", ",") or ","
    quote = opts.get("quote", '"') or '"'
    rd = opts.get("record_delim", "\n")
    fields = []
    for v in row.values():
        s = _fmt(v)
        if delim in s or quote in s or "\n" in s or "\r" in s:
            s = quote + s.replace(quote, quote + quote) + quote
        fields.append(s)
    return (delim.join(fields) + rd).encode()


def to_json_record(row: dict, opts: dict) -> bytes:
    rd = opts.get("record_delim", "\n")
    clean = {k: v for k, v in row.items()}
    # compact separators: the service emits no whitespace in JSON
    # output records (observable AWS behavior; select.go json writer)
    return (_json.dumps(clean, default=str, separators=(",", ":"))
            + rd).encode()
