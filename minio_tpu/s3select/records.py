"""Record readers/writers for S3 Select (pkg/s3select/csv/, json/).

CSV input honors FileHeaderInfo USE/IGNORE/NONE, custom delimiters and
quotes (pkg/s3select/csv/args.go); JSON input handles LINES and DOCUMENT
types (pkg/s3select/json/args.go).  Positional columns are always
available as _1.._N, matching the reference's column addressing.
"""

from __future__ import annotations

import csv as _csv
import io
import json as _json
from typing import Iterator


def csv_records(data: bytes, opts: dict) -> Iterator[dict]:
    return csv_records_stream((data,), opts)


def csv_records_stream(blocks, opts: dict) -> Iterator[dict]:
    """CSV rows over an iterator of byte blocks, each holding COMPLETE
    records (a :class:`RecordChunker` upstream guarantees no record —
    even a quoted multi-line field — straddles a block).  Header state
    carries across blocks, so block boundaries are invisible to the
    caller; one-block input is exactly the old whole-buffer reader."""
    rd = opts.get("record_delim", "\n")
    comment = opts.get("comment") or None
    header_mode = opts.get("header", "NONE")
    headers: list[str] | None = None
    saw_first = False                # first NON-skipped row is the header
    for block in blocks:
        text = block.decode("utf-8", errors="replace")
        if rd not in ("\n", "\r\n"):
            text = text.replace(rd, "\n")
        reader = _csv.reader(
            io.StringIO(text),
            delimiter=opts.get("field_delim", ",") or ",",
            quotechar=opts.get("quote", '"') or '"')
        for fields in reader:
            if not fields:
                continue
            if comment and fields[0].startswith(comment):
                continue
            if not saw_first:
                saw_first = True
                if header_mode == "USE":
                    headers = [h.strip() for h in fields]
                    continue
                if header_mode == "IGNORE":
                    continue
            # named keys only when headers exist — SELECT * must not
            # emit columns twice; _N positional addressing is resolved
            # by the SQL evaluator's index fallback
            row: dict = {}
            for j, v in enumerate(fields):
                if headers and j < len(headers):
                    row[headers[j]] = v
                else:
                    row[f"_{j + 1}"] = v
            yield row


class RecordChunker:
    """Splits an arbitrary byte stream into blocks of COMPLETE records
    — the streaming scanner's framing layer.  ``feed`` returns every
    byte up to (and including) the last record delimiter that sits
    OUTSIDE a quoted field, retaining the tail; ``flush`` returns the
    final partial record.  Quoting follows the csv module's reader
    rules EXACTLY (the whole-buffer parser the streamed path must stay
    byte-identical to): a quote opens a quoted field only at FIELD
    START (buffer start, after the field delimiter, or after the
    record delimiter) — a stray mid-field quote is literal; inside
    quotes a doubled quote is an escaped literal and any other quote
    closes the field.  State is per-buffer only: the buffer always
    begins outside quotes because cuts only happen at outside-quote
    delimiters.  Pass ``quote=None`` for quote-free formats (JSON
    Lines)."""

    def __init__(self, record_delim: bytes = b"\n",
                 quote: bytes | None = b'"',
                 field_delim: bytes = b","):
        self._delim = record_delim or b"\n"
        self._quote = quote if quote else None
        self._field_delim = field_delim or b","
        self._buf = bytearray()

    def feed(self, data: bytes) -> bytes:
        self._buf += data
        buf = self._buf
        if self._quote is None or self._quote not in buf:
            cut = buf.rfind(self._delim)
            if cut < 0:
                return b""
            cut += len(self._delim)
            out = bytes(buf[:cut])
            del buf[:cut]
            return out
        # quoted content present: split at quote chars (C speed) and
        # classify each inter-quote segment inside/outside by walking
        # the O(#quotes) boundary list with the csv rules above, then
        # take the LAST delimiter in an outside segment
        raw = bytes(buf)
        segs = raw.split(self._quote)
        qlen = len(self._quote)
        n = len(segs)
        offsets = []
        pos = 0
        for s in segs:
            offsets.append(pos)
            pos += len(s) + qlen
        inside = [False] * n            # seg 0 starts outside
        fd = self._field_delim[-1:]
        rd = self._delim[-1:]
        in_q = False
        j = 1
        while j < n:
            if not in_q:
                p = offsets[j] - qlen   # this boundary's quote char
                prev = raw[p - 1:p] if p else b""
                if p == 0 or prev == fd or prev == rd:
                    in_q = True         # opening quote at field start
                # else: literal mid-field quote, no state change
                inside[j] = in_q
                j += 1
            elif segs[j] == b"":
                # adjacent quote: "" escape (still inside) — or, when
                # the quote is the buffer's LAST byte, an AMBIGUOUS
                # close-vs-escape whose other half may arrive next
                # feed: defer (stay inside; a cut can always wait for
                # more data, the re-scan then decides with context)
                inside[j] = True
                j += 1
                if j < n:
                    inside[j] = True
                    j += 1
            else:
                in_q = False            # closing quote
                inside[j] = False
                j += 1
        cut = -1
        for i in range(n - 1, -1, -1):
            if inside[i]:
                continue
            k = segs[i].rfind(self._delim)
            if k >= 0:
                cut = offsets[i] + k
                break
        if cut < 0:
            return b""
        cut += len(self._delim)
        out = bytes(buf[:cut])
        del buf[:cut]
        return out

    def flush(self) -> bytes:
        out = bytes(self._buf)
        self._buf = bytearray()
        return out


def record_blocks(chunks, record_delim: bytes = b"\n",
                  quote: bytes | None = b'"',
                  field_delim: bytes = b",") -> Iterator[bytes]:
    """Re-frame a byte-chunk iterator at record boundaries: yields one
    block of complete records per input chunk (skipping chunks that
    completed none), then the final partial record."""
    from ..utils import close_quietly
    ck = RecordChunker(record_delim, quote, field_delim)
    try:
        for chunk in chunks:
            block = ck.feed(chunk)
            if block:
                yield block
        tail = ck.flush()
        if tail:
            yield tail
    finally:
        close_quietly(chunks)


_SCAN_LIB = None
_SCAN_TRIED = False
_OPS = {"=": 0, "!=": 1, "<>": 1, "<": 2, "<=": 3, ">": 4, ">=": 5}


def _scan_lib():
    global _SCAN_LIB, _SCAN_TRIED
    if _SCAN_TRIED:
        return _SCAN_LIB
    import ctypes
    import os as _os

    from ..utils import nativelib
    src = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))), "native",
        "jsonscan.cc")
    so = _os.path.join(_os.path.dirname(src), "build", "libmtjscan.so")
    lib = nativelib.load(src, so)
    if lib is not None:
        lib.mt_ndjson_filter.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t), ctypes.c_long]
        lib.mt_ndjson_filter.restype = ctypes.c_long
    _SCAN_LIB = lib
    _SCAN_TRIED = True
    return lib


def ndjson_prefilter(data: bytes, field: str, op: str,
                     value) -> list[tuple[int, int]] | None:
    """Byte ranges of NDJSON rows that MIGHT satisfy `field op value`
    (native/jsonscan.cc — the simdjson-role scanner): conservative-
    exact, so callers re-evaluate the full WHERE on survivors.  None =
    fast path unavailable (no native lib / unsupported op or type)."""
    import ctypes
    lib = _scan_lib()
    if lib is None or op not in _OPS:
        return None
    if isinstance(value, bool) or value is None:
        return None
    if isinstance(value, (int, float)):
        kind, num, sval = 0, float(value), b""
    elif isinstance(value, str):
        kind, num, sval = 1, 0.0, value.encode()
    else:
        return None
    cap = max(1024, data.count(b"\n") + 2)
    while True:
        out = (ctypes.c_size_t * (2 * cap))()
        got = lib.mt_ndjson_filter(
            data, len(data), field.encode(), len(field.encode()),
            _OPS[op], kind, num, sval, len(sval), out, cap)
        if got >= 0:
            return [(out[2 * i], out[2 * i + 1]) for i in range(got)]
        cap *= 2


def json_records(data: bytes, opts: dict) -> Iterator[dict]:
    jtype = opts.get("type", "LINES")
    text = data.decode("utf-8", errors="replace")
    if jtype == "LINES":
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            yield _wrap(_json.loads(line))
    else:  # DOCUMENT: one value, or concatenated values
        dec = _json.JSONDecoder()
        i, n = 0, len(text)
        while i < n:
            while i < n and text[i].isspace():
                i += 1
            if i >= n:
                break
            obj, end = dec.raw_decode(text, i)
            i = end
            if isinstance(obj, list):
                for item in obj:
                    yield _wrap(item)
            else:
                yield _wrap(obj)


def _wrap(obj) -> dict:
    if isinstance(obj, dict):
        return obj
    return {"_1": obj}


def _fmt(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def to_csv_record(row: dict, opts: dict) -> bytes:
    delim = opts.get("field_delim", ",") or ","
    quote = opts.get("quote", '"') or '"'
    rd = opts.get("record_delim", "\n")
    fields = []
    for v in row.values():
        s = _fmt(v)
        if delim in s or quote in s or "\n" in s or "\r" in s:
            s = quote + s.replace(quote, quote + quote) + quote
        fields.append(s)
    return (delim.join(fields) + rd).encode()


def to_json_record(row: dict, opts: dict) -> bytes:
    rd = opts.get("record_delim", "\n")
    clean = {k: v for k, v in row.items()}
    # compact separators: the service emits no whitespace in JSON
    # output records (observable AWS behavior; select.go json writer)
    return (_json.dumps(clean, default=str, separators=(",", ":"))
            + rd).encode()
