"""AWS event-stream framing for SelectObjectContent responses.

Reference: pkg/s3select/message.go (newRecordsMessage, newStatsMessage,
newEndMessage and the prelude/CRC layout).  Wire format per message:

    4B total length (BE) | 4B headers length (BE) | 4B CRC32(prelude)
    headers | payload | 4B CRC32(everything before)

Header encoding: 1B name length, name, 1B value type (7 = string),
2B value length (BE), value.
"""

from __future__ import annotations

import struct
import zlib


def _header(name: str, value: str) -> bytes:
    nb, vb = name.encode(), value.encode()
    return bytes([len(nb)]) + nb + b"\x07" + struct.pack(">H", len(vb)) + vb


def _message(headers: list[tuple[str, str]], payload: bytes) -> bytes:
    hdr = b"".join(_header(n, v) for n, v in headers)
    total = 12 + len(hdr) + len(payload) + 4
    prelude = struct.pack(">II", total, len(hdr))
    prelude_crc = struct.pack(">I", zlib.crc32(prelude))
    body = prelude + prelude_crc + hdr + payload
    return body + struct.pack(">I", zlib.crc32(body))


def records_event(payload: bytes) -> bytes:
    return _message([
        (":message-type", "event"),
        (":event-type", "Records"),
        (":content-type", "application/octet-stream"),
    ], payload)


def continuation_event() -> bytes:
    return _message([
        (":message-type", "event"),
        (":event-type", "Cont"),
    ], b"")


def progress_event(scanned: int, processed: int, returned: int) -> bytes:
    xml = (f"<Progress><BytesScanned>{scanned}</BytesScanned>"
           f"<BytesProcessed>{processed}</BytesProcessed>"
           f"<BytesReturned>{returned}</BytesReturned></Progress>")
    return _message([
        (":message-type", "event"),
        (":event-type", "Progress"),
        (":content-type", "text/xml"),
    ], xml.encode())


def stats_event(scanned: int, processed: int, returned: int) -> bytes:
    xml = (f"<Stats><BytesScanned>{scanned}</BytesScanned>"
           f"<BytesProcessed>{processed}</BytesProcessed>"
           f"<BytesReturned>{returned}</BytesReturned></Stats>")
    return _message([
        (":message-type", "event"),
        (":event-type", "Stats"),
        (":content-type", "text/xml"),
    ], xml.encode())


def end_event() -> bytes:
    return _message([
        (":message-type", "event"),
        (":event-type", "End"),
    ], b"")


def error_message(code: str, description: str) -> bytes:
    return _message([
        (":message-type", "error"),
        (":error-code", code),
        (":error-message", description),
    ], b"")


def parse_events(stream: bytes) -> list[tuple[str, bytes]]:
    """Decode a framed stream into [(event_type, payload)] — used by the
    client/tests (mint's response parsing analog).  Validates CRCs."""
    out = []
    i = 0
    while i < len(stream):
        if i + 12 > len(stream):
            raise ValueError("truncated prelude")
        total, hlen = struct.unpack(">II", stream[i:i + 8])
        crc = struct.unpack(">I", stream[i + 8:i + 12])[0]
        if zlib.crc32(stream[i:i + 8]) != crc:
            raise ValueError("prelude CRC mismatch")
        if i + total > len(stream):
            raise ValueError("truncated message")
        msg = stream[i:i + total]
        if zlib.crc32(msg[:-4]) != struct.unpack(">I", msg[-4:])[0]:
            raise ValueError("message CRC mismatch")
        headers = {}
        j = 12
        while j < 12 + hlen:
            nl = msg[j]
            name = msg[j + 1:j + 1 + nl].decode()
            j += 1 + nl
            vtype = msg[j]
            j += 1
            if vtype != 7:
                raise ValueError(f"unsupported header type {vtype}")
            vl = struct.unpack(">H", msg[j:j + 2])[0]
            headers[name] = msg[j + 2:j + 2 + vl].decode()
            j += 2 + vl
        payload = msg[12 + hlen:-4]
        out.append((headers.get(":event-type",
                                headers.get(":error-code", "?")), payload))
        i += total
    return out
