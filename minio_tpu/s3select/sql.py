"""S3 Select SQL: lexer, recursive-descent parser, evaluator.

Reference: pkg/s3select/sql/ (participle-generated parser in parser.go,
evaluation in evaluate.go, aggregates in aggregation.go, functions in
funceval.go).  Supported surface (the S3 Select dialect — one table, no
joins, no GROUP BY):

    SELECT <* | expr [AS alias], ...>
    FROM S3Object[.path] [[AS] alias]
    [WHERE <expr>] [LIMIT n]

Expressions: literals, column refs (names, "quoted", _N positional,
alias.col), arithmetic + - * / %, comparisons = != <> < <= > >=,
AND/OR/NOT, LIKE [ESCAPE], IN (...), BETWEEN, IS [NOT] NULL,
CAST(x AS t), COALESCE, NULLIF, string functions (LOWER/UPPER/TRIM/
CHAR_LENGTH/CHARACTER_LENGTH/SUBSTRING), and aggregates COUNT/SUM/AVG/
MIN/MAX.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Iterable, Iterator, Optional


class SQLError(Exception):
    pass


# ---------------------------------------------------------------- lexer ----

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+)
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<string>'(?:[^']|'')*')
  | (?P<op><>|!=|<=|>=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.|\[|\])
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
""", re.VERBOSE)

KEYWORDS = {
    "SELECT", "FROM", "WHERE", "LIMIT", "AS", "AND", "OR", "NOT", "LIKE",
    "ESCAPE", "IN", "BETWEEN", "IS", "NULL", "TRUE", "FALSE", "CAST",
    "COALESCE", "NULLIF", "COUNT", "SUM", "AVG", "MIN", "MAX",
}


@dataclass
class Token:
    kind: str      # number|string|ident|qident|op|kw|eof
    value: str


def tokenize(text: str) -> list[Token]:
    out: list[Token] = []
    i = 0
    while i < len(text):
        m = _TOKEN_RE.match(text, i)
        if not m:
            raise SQLError(f"unexpected character {text[i]!r} at {i}")
        i = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        v = m.group()
        if kind == "ident" and v.upper() in KEYWORDS:
            out.append(Token("kw", v.upper()))
        else:
            out.append(Token(kind, v))
    out.append(Token("eof", ""))
    return out


# ------------------------------------------------------------------ AST ----

@dataclass
class Literal:
    value: Any


@dataclass
class Column:
    path: list[str]        # ["alias", "a", "b"] → row["a"]["b"] after alias


@dataclass
class Unary:
    op: str
    operand: Any


@dataclass
class Binary:
    op: str
    left: Any
    right: Any


@dataclass
class Between:
    expr: Any
    lo: Any
    hi: Any
    negate: bool


@dataclass
class Like:
    expr: Any
    pattern: Any
    escape: Optional[str]
    negate: bool


@dataclass
class InList:
    expr: Any
    items: list
    negate: bool


@dataclass
class IsNull:
    expr: Any
    negate: bool


@dataclass
class Cast:
    expr: Any
    type: str


@dataclass
class Func:
    name: str
    args: list
    star: bool = False     # COUNT(*)


@dataclass
class Projection:
    expr: Any              # None for SELECT *
    alias: str


@dataclass
class Query:
    projections: list[Projection]   # empty = SELECT *
    table_alias: str
    where: Any
    limit: Optional[int]
    aggregate: bool


AGGREGATES = {"COUNT", "SUM", "AVG", "MIN", "MAX"}
SCALAR_FUNCS = {"LOWER", "UPPER", "TRIM", "CHAR_LENGTH",
                "CHARACTER_LENGTH", "LENGTH", "SUBSTRING", "COALESCE",
                "NULLIF", "UTCNOW", "ABS"}


class Parser:
    def __init__(self, tokens: list[Token]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, value: str | None = None) -> Optional[Token]:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind: str, value: str | None = None) -> Token:
        t = self.accept(kind, value)
        if t is None:
            raise SQLError(f"expected {value or kind}, "
                           f"got {self.peek().value!r}")
        return t

    # -- grammar ----------------------------------------------------------

    def parse_query(self) -> Query:
        self.expect("kw", "SELECT")
        projections: list[Projection] = []
        if self.accept("op", "*"):
            pass
        else:
            while True:
                e = self.expr()
                alias = ""
                if self.accept("kw", "AS"):
                    alias = self._ident_name()
                elif self.peek().kind in ("ident", "qident"):
                    alias = self._ident_name()
                projections.append(Projection(e, alias))
                if not self.accept("op", ","):
                    break
        self.expect("kw", "FROM")
        table_alias = self._from_clause()
        where = None
        if self.accept("kw", "WHERE"):
            where = self.expr()
        limit = None
        if self.accept("kw", "LIMIT"):
            t = self.expect("number")
            limit = int(float(t.value))
        self.expect("eof")
        has_agg = any(self._has_aggregate(p.expr) for p in projections)
        if has_agg and not all(self._has_aggregate(p.expr)
                               for p in projections):
            raise SQLError("cannot mix aggregate and non-aggregate "
                           "projections")
        return Query(projections, table_alias, where, limit, has_agg)

    def _ident_name(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            return self.next().value
        if t.kind == "qident":
            return self.next().value[1:-1].replace('""', '"')
        raise SQLError(f"expected identifier, got {t.value!r}")

    def _from_clause(self) -> str:
        name = self._ident_name()
        if name.lower() not in ("s3object", "s3objects"):
            raise SQLError("FROM must reference S3Object")
        if self.accept("op", "["):      # FROM S3Object[*] — the JSON
            self.expect("op", "*")      # document-array form
            self.expect("op", "]")
        while self.accept("op", "."):   # S3Object.path — path ignored for
            self._ident_name()          # flat records (JMESPath-ish)
        if self.accept("kw", "AS"):
            return self._ident_name()
        if self.peek().kind in ("ident", "qident"):
            return self._ident_name()
        return ""

    def _has_aggregate(self, node) -> bool:
        if isinstance(node, Func) and node.name in AGGREGATES:
            return True
        for f in getattr(node, "__dataclass_fields__", {}):
            v = getattr(node, f)
            if isinstance(v, list):
                if any(self._has_aggregate(x) for x in v
                       if hasattr(x, "__dataclass_fields__")):
                    return True
            elif hasattr(v, "__dataclass_fields__") and \
                    self._has_aggregate(v):
                return True
        return False

    def expr(self):
        return self.or_expr()

    def or_expr(self):
        left = self.and_expr()
        while self.accept("kw", "OR"):
            left = Binary("OR", left, self.and_expr())
        return left

    def and_expr(self):
        left = self.not_expr()
        while self.accept("kw", "AND"):
            left = Binary("AND", left, self.not_expr())
        return left

    def not_expr(self):
        if self.accept("kw", "NOT"):
            return Unary("NOT", self.not_expr())
        return self.cmp_expr()

    def cmp_expr(self):
        left = self.add_expr()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "!=", "<>", "<", "<=", ">",
                                          ">="):
            self.next()
            op = "!=" if t.value == "<>" else t.value
            return Binary(op, left, self.add_expr())
        negate = False
        if t.kind == "kw" and t.value == "NOT" and \
                self.toks[self.i + 1].kind == "kw" and \
                self.toks[self.i + 1].value in ("LIKE", "IN", "BETWEEN"):
            self.next()
            negate = True
            t = self.peek()
        if self.accept("kw", "BETWEEN"):
            lo = self.add_expr()
            self.expect("kw", "AND")
            return Between(left, lo, self.add_expr(), negate)
        if self.accept("kw", "LIKE"):
            pattern = self.add_expr()
            esc = None
            if self.accept("kw", "ESCAPE"):
                e = self.expect("string")
                esc = e.value[1:-1].replace("''", "'")
            return Like(left, pattern, esc, negate)
        if self.accept("kw", "IN"):
            self.expect("op", "(")
            items = [self.expr()]
            while self.accept("op", ","):
                items.append(self.expr())
            self.expect("op", ")")
            return InList(left, items, negate)
        if self.accept("kw", "IS"):
            neg = bool(self.accept("kw", "NOT"))
            self.expect("kw", "NULL")
            return IsNull(left, neg)
        return left

    def add_expr(self):
        left = self.mul_expr()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                left = Binary(t.value, left, self.mul_expr())
            else:
                return left

    def mul_expr(self):
        left = self.unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                left = Binary(t.value, left, self.unary())
            else:
                return left

    def unary(self):
        t = self.peek()
        if t.kind == "op" and t.value in ("-", "+"):
            self.next()
            return Unary(t.value, self.unary())
        return self.primary()

    def primary(self):
        t = self.peek()
        if t.kind == "number":
            self.next()
            v = float(t.value)
            return Literal(int(v) if v.is_integer() and
                           "." not in t.value and "e" not in t.value.lower()
                           else v)
        if t.kind == "string":
            self.next()
            return Literal(t.value[1:-1].replace("''", "'"))
        if t.kind == "kw" and t.value in ("TRUE", "FALSE"):
            self.next()
            return Literal(t.value == "TRUE")
        if t.kind == "kw" and t.value == "NULL":
            self.next()
            return Literal(None)
        if t.kind == "kw" and t.value == "CAST":
            self.next()
            self.expect("op", "(")
            e = self.expr()
            self.expect("kw", "AS")
            ty = self._ident_name().upper()
            self.expect("op", ")")
            return Cast(e, ty)
        if t.kind == "kw" and t.value in AGGREGATES:
            self.next()
            self.expect("op", "(")
            if t.value == "COUNT" and self.accept("op", "*"):
                self.expect("op", ")")
                return Func("COUNT", [], star=True)
            arg = self.expr()
            self.expect("op", ")")
            return Func(t.value, [arg])
        if t.kind == "kw" and t.value in ("COALESCE", "NULLIF"):
            self.next()
            self.expect("op", "(")
            args = [self.expr()]
            while self.accept("op", ","):
                args.append(self.expr())
            self.expect("op", ")")
            return Func(t.value, args)
        if t.kind in ("ident", "qident"):
            name = self._ident_name()
            if self.peek().kind == "op" and self.peek().value == "(":
                if name.upper() not in SCALAR_FUNCS:
                    raise SQLError(f"unknown function {name}")
                self.next()
                args = []
                if not self.accept("op", ")"):
                    args.append(self.expr())
                    while self.accept("op", ","):
                        args.append(self.expr())
                    # SUBSTRING(x FROM n FOR m) — also accept comma form
                    self.expect("op", ")")
                return Func(name.upper(), args)
            path = [name]
            while self.accept("op", "."):
                path.append(self._ident_name())
            return Column(path)
        if self.accept("op", "("):
            e = self.expr()
            self.expect("op", ")")
            return e
        raise SQLError(f"unexpected token {t.value!r}")


def parse_query(text: str) -> Query:
    return Parser(tokenize(text)).parse_query()


# ------------------------------------------------------------- evaluator ---

_MISSING = object()


def _lookup(row: dict, path: list[str], table_alias: str):
    # strip the table alias / S3Object prefix
    parts = list(path)
    if parts and (parts[0] == table_alias or
                  parts[0].lower() in ("s3object", "s3objects")):
        parts = parts[1:]
    cur: Any = row
    for p in parts:
        if isinstance(cur, dict):
            if p in cur:
                cur = cur[p]
            elif p.lower() in cur:
                cur = cur[p.lower()]
            elif re.fullmatch(r"_\d+", p):
                # positional fallback: _N addresses the Nth column even
                # when the reader produced named keys (FileHeaderInfo=USE)
                idx = int(p[1:]) - 1
                vals = list(cur.values())
                if 0 <= idx < len(vals):
                    cur = vals[idx]
                else:
                    return _MISSING
            else:
                return _MISSING
        elif isinstance(cur, list) and p.isdigit():
            idx = int(p)
            cur = cur[idx] if idx < len(cur) else _MISSING
        else:
            return _MISSING
    return cur


def _num(v):
    """Numeric coercion for arithmetic/comparison (CSV values are text)."""
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        try:
            f = float(v)
            return int(f) if f.is_integer() and "." not in v \
                and "e" not in v.lower() else f
        except ValueError:
            return None
    return None


def _compare(op: str, a, b) -> Optional[bool]:
    if a is None or b is None or a is _MISSING or b is _MISSING:
        return None
    na, nb = _num(a), _num(b)
    if isinstance(a, str) and isinstance(b, str):
        pass                      # string-vs-string stays textual
    elif na is not None and nb is not None:
        a, b = na, nb             # mixed string/number: numeric coercion
    elif isinstance(a, str) or isinstance(b, str):
        a, b = str(a), str(b)
    try:
        if op == "=":
            return a == b
        if op == "!=":
            return a != b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
    except TypeError:
        return None
    raise SQLError(f"bad comparison {op}")


def _like_to_re(pattern: str, escape: Optional[str]) -> re.Pattern:
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


class Evaluator:
    def __init__(self, query: Query):
        self.q = query

    def eval(self, node, row: dict):
        if isinstance(node, Literal):
            return node.value
        if isinstance(node, Column):
            v = _lookup(row, node.path, self.q.table_alias)
            return None if v is _MISSING else v
        if isinstance(node, Unary):
            v = self.eval(node.operand, row)
            if node.op == "NOT":
                b = self._truth(v)
                return None if b is None else not b
            n = _num(v)
            if n is None:
                return None
            return -n if node.op == "-" else n
        if isinstance(node, Binary):
            if node.op in ("AND", "OR"):
                a = self._truth(self.eval(node.left, row))
                b = self._truth(self.eval(node.right, row))
                if node.op == "AND":
                    if a is False or b is False:
                        return False
                    return None if a is None or b is None else True
                if a is True or b is True:
                    return True
                return None if a is None or b is None else False
            if node.op in ("=", "!=", "<", "<=", ">", ">="):
                return _compare(node.op, self.eval(node.left, row),
                                self.eval(node.right, row))
            a, b = _num(self.eval(node.left, row)), \
                _num(self.eval(node.right, row))
            if a is None or b is None:
                return None
            try:
                if node.op == "+":
                    return a + b
                if node.op == "-":
                    return a - b
                if node.op == "*":
                    return a * b
                if node.op == "/":
                    r = a / b
                    return int(r) if isinstance(a, int) and \
                        isinstance(b, int) and a % b == 0 else r
                if node.op == "%":
                    return a % b
            except ZeroDivisionError as e:
                raise SQLError("division by zero") from e
        if isinstance(node, Between):
            v = self.eval(node.expr, row)
            lo = _compare(">=", v, self.eval(node.lo, row))
            hi = _compare("<=", v, self.eval(node.hi, row))
            if lo is None or hi is None:
                return None
            res = lo and hi
            return not res if node.negate else res
        if isinstance(node, Like):
            v = self.eval(node.expr, row)
            p = self.eval(node.pattern, row)
            if v is None or p is None:
                return None
            res = bool(_like_to_re(str(p), node.escape).match(str(v)))
            return not res if node.negate else res
        if isinstance(node, InList):
            v = self.eval(node.expr, row)
            found = False
            for item in node.items:
                c = _compare("=", v, self.eval(item, row))
                if c:
                    found = True
                    break
            return not found if node.negate else found
        if isinstance(node, IsNull):
            v = self.eval(node.expr, row)
            res = v is None or v is _MISSING
            return not res if node.negate else res
        if isinstance(node, Cast):
            return self._cast(self.eval(node.expr, row), node.type)
        if isinstance(node, Func):
            return self._func(node, row)
        raise SQLError(f"cannot evaluate {node!r}")

    @staticmethod
    def _truth(v) -> Optional[bool]:
        if v is None or v is _MISSING:
            return None
        if isinstance(v, bool):
            return v
        if isinstance(v, str):
            if v.lower() == "true":
                return True
            if v.lower() == "false":
                return False
        return bool(v)

    @staticmethod
    def _cast(v, ty: str):
        if v is None:
            return None
        try:
            if ty in ("INT", "INTEGER"):
                return int(float(v))
            if ty in ("FLOAT", "DOUBLE", "DECIMAL", "NUMERIC"):
                return float(v)
            if ty in ("STRING", "VARCHAR", "CHAR"):
                return str(v)
            if ty in ("BOOL", "BOOLEAN"):
                if isinstance(v, str):
                    return v.lower() == "true"
                return bool(v)
        except (ValueError, TypeError) as e:
            raise SQLError(f"cannot CAST {v!r} to {ty}") from e
        raise SQLError(f"unknown CAST type {ty}")

    def _func(self, node: Func, row: dict):
        name = node.name
        args = [self.eval(a, row) for a in node.args]
        if name == "LOWER":
            return None if args[0] is None else str(args[0]).lower()
        if name == "UPPER":
            return None if args[0] is None else str(args[0]).upper()
        if name == "TRIM":
            return None if args[0] is None else str(args[0]).strip()
        if name in ("CHAR_LENGTH", "CHARACTER_LENGTH", "LENGTH"):
            return None if args[0] is None else len(str(args[0]))
        if name == "SUBSTRING":
            if args[0] is None:
                return None
            s = str(args[0])
            start = int(args[1]) if len(args) > 1 else 1
            start = max(start, 1)
            if len(args) > 2:
                return s[start - 1:start - 1 + int(args[2])]
            return s[start - 1:]
        if name == "COALESCE":
            for a in args:
                if a is not None:
                    return a
            return None
        if name == "NULLIF":
            return None if _compare("=", args[0], args[1]) else args[0]
        if name == "ABS":
            n = _num(args[0])
            return None if n is None else abs(n)
        if name == "UTCNOW":
            import datetime
            return datetime.datetime.now(
                datetime.timezone.utc).isoformat()
        raise SQLError(f"unknown function {name}")


# -- aggregation ------------------------------------------------------------

class _Agg:
    def __init__(self, kind: str):
        self.kind = kind
        self.count = 0
        self.total: Any = 0
        self.min: Any = None
        self.max: Any = None

    def add(self, v):
        if self.kind == "COUNT":
            if v is not None and v is not _MISSING:   # SQL: skip NULLs
                self.count += 1
            return
        if v is None or v is _MISSING:
            return
        n = _num(v)
        self.count += 1
        if n is not None:
            self.total += n
        if self.min is None or _compare("<", v, self.min):
            self.min = v
        if self.max is None or _compare(">", v, self.max):
            self.max = v

    def result(self):
        if self.kind == "COUNT":
            return self.count
        if self.count == 0:
            return None
        if self.kind == "SUM":
            return self.total
        if self.kind == "AVG":
            return self.total / self.count
        if self.kind == "MIN":
            return self.min
        if self.kind == "MAX":
            return self.max
        raise SQLError(f"unknown aggregate {self.kind}")


def execute(query: Query, rows: Iterable[dict]) -> Iterator[dict]:
    """Run the query over records; yields output rows (ordered dicts)."""
    ev = Evaluator(query)
    if query.aggregate:
        aggs: list[tuple[Projection, Func, _Agg]] = []
        for p in query.projections:
            if not isinstance(p.expr, Func) or p.expr.name not in AGGREGATES:
                raise SQLError("aggregate queries must project aggregates")
            aggs.append((p, p.expr, _Agg(p.expr.name)))
        for row in rows:
            if query.where is not None and \
                    ev.eval(query.where, row) is not True:
                continue
            for _, fn, st in aggs:
                if fn.star:
                    st.count += 1
                else:
                    st.add(ev.eval(fn.args[0], row))
        if query.limit == 0:
            return
        out = {}
        for idx, (p, fn, st) in enumerate(aggs):
            out[p.alias or f"_{idx + 1}"] = st.result()
        yield out
        return

    emitted = 0
    for row in rows:
        if query.limit is not None and emitted >= query.limit:
            return
        if query.where is not None and \
                ev.eval(query.where, row) is not True:
            continue
        if not query.projections:            # SELECT *
            yield row
        else:
            out = {}
            for idx, p in enumerate(query.projections):
                name = p.alias
                if not name and isinstance(p.expr, Column):
                    name = p.expr.path[-1]
                out[name or f"_{idx + 1}"] = ev.eval(p.expr, row)
            yield out
        emitted += 1
        if query.limit is not None and emitted >= query.limit:
            return
