"""Minimal Parquet reader/writer for S3 Select.

Reference scope: pkg/s3select/internal/parquet-go (the bundled reader
used by select.go's parquet input serialization).  This implements the
subset S3 Select needs — flat schemas, data page v1, PLAIN and
RLE_DICTIONARY/PLAIN_DICTIONARY encodings, UNCOMPRESSED and SNAPPY page
codecs (via minio_tpu.compress, the same native codec the object path
uses) — plus a writer producing standard files for tests and tooling.

Format essentials:
  file   = "PAR1" pages... FileMetaData(thrift compact) len(4 LE) "PAR1"
  page   = PageHeader(thrift compact) [compressed] page body
  v1 data page body (flat) = [def levels: RLE hybrid w/ 4-byte len]
                             [values: PLAIN or dict indices]
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

from .. import compress as mtc

MAGIC = b"PAR1"

# parquet.thrift Type enum
BOOLEAN, INT32, INT64, INT96, FLOAT, DOUBLE, BYTE_ARRAY, FIXED_LEN = range(8)
# CompressionCodec
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_GZIP = 0, 1, 2
# Encoding
ENC_PLAIN, ENC_PLAIN_DICT, ENC_RLE = 0, 2, 3
ENC_RLE_DICT = 8
# PageType
PAGE_DATA, PAGE_INDEX, PAGE_DICT, PAGE_DATA_V2 = 0, 1, 2, 3
# FieldRepetitionType
REQUIRED, OPTIONAL, REPEATED = 0, 1, 2
# ConvertedType (subset)
CT_UTF8 = 0


class ParquetError(ValueError):
    """ValueError so mid-stream decode failures surface as a parse
    error (400) through run_select's reader error handling."""


# ---------------------------------------------------------------------------
# Thrift compact protocol (decode + encode, the subset parquet uses)
# ---------------------------------------------------------------------------

CT_STOP, CT_TRUE, CT_FALSE, CT_BYTE, CT_I16, CT_I32, CT_I64, CT_DOUBLE, \
    CT_BINARY, CT_LIST, CT_SET, CT_MAP, CT_STRUCT = range(13)


class TReader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        out = shift = 0
        while True:
            b = self.byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        n = self.varint()
        return (n >> 1) ^ -(n & 1)

    def binary(self) -> bytes:
        n = self.varint()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def double(self) -> float:
        v = struct.unpack_from("<d", self.buf, self.pos)[0]
        self.pos += 8
        return v

    def skip(self, ftype: int) -> None:
        if ftype in (CT_TRUE, CT_FALSE):
            return
        if ftype == CT_BYTE:
            self.pos += 1
        elif ftype in (CT_I16, CT_I32, CT_I64):
            self.varint()
        elif ftype == CT_DOUBLE:
            self.pos += 8
        elif ftype == CT_BINARY:
            self.binary()
        elif ftype in (CT_LIST, CT_SET):
            size, etype = self.list_header()
            for _ in range(size):
                self.skip(etype)
        elif ftype == CT_STRUCT:
            for fid, ft in self.fields():
                self.skip(ft)
        else:
            raise ParquetError(f"cannot skip thrift type {ftype}")

    def list_header(self) -> tuple[int, int]:
        b = self.byte()
        size = b >> 4
        if size == 15:
            size = self.varint()
        return size, b & 0x0F

    def fields(self) -> Iterator[tuple[int, int]]:
        """Yield (field id, type) until STOP; caller reads/skips value.
        Boolean values are encoded in the type (CT_TRUE/CT_FALSE)."""
        fid = 0
        while True:
            b = self.byte()
            if b == CT_STOP:
                return
            delta = b >> 4
            ftype = b & 0x0F
            fid = fid + delta if delta else self.zigzag()
            yield fid, ftype


class TWriter:
    def __init__(self):
        self.out = bytearray()
        self._fid_stack: list[int] = []
        self._fid = 0

    def varint(self, n: int) -> None:
        while True:
            if n < 0x80:
                self.out.append(n)
                return
            self.out.append((n & 0x7F) | 0x80)
            n >>= 7

    def zigzag(self, n: int) -> None:
        self.varint((n << 1) ^ (n >> 63) if n >= 0 else ((-n) << 1) - 1)

    def struct_begin(self) -> None:
        self._fid_stack.append(self._fid)
        self._fid = 0

    def struct_end(self) -> None:
        self.out.append(CT_STOP)
        self._fid = self._fid_stack.pop()

    def field(self, fid: int, ftype: int) -> None:
        delta = fid - self._fid
        if 0 < delta <= 15:
            self.out.append((delta << 4) | ftype)
        else:
            self.out.append(ftype)
            self.zigzag(fid)
        self._fid = fid

    def i32(self, fid: int, v: int) -> None:
        self.field(fid, CT_I32)
        self.zigzag(v)

    def i64(self, fid: int, v: int) -> None:
        self.field(fid, CT_I64)
        self.zigzag(v)

    def binary(self, fid: int, v: bytes) -> None:
        self.field(fid, CT_BINARY)
        self.varint(len(v))
        self.out += v

    def list_begin(self, fid: int, etype: int, size: int) -> None:
        self.field(fid, CT_LIST)
        if size < 15:
            self.out.append((size << 4) | etype)
        else:
            self.out.append(0xF0 | etype)
            self.varint(size)


# ---------------------------------------------------------------------------
# metadata model
# ---------------------------------------------------------------------------

@dataclass
class Column:
    name: str
    type: int                      # parquet physical type
    repetition: int = REQUIRED
    converted: Optional[int] = None     # CT_UTF8 for strings


@dataclass
class _ChunkMeta:
    type: int = 0
    codec: int = 0
    num_values: int = 0
    data_page_offset: int = 0
    dict_page_offset: Optional[int] = None
    total_compressed_size: int = 0
    path: list[str] = field(default_factory=list)


def _decode_schema(r: TReader) -> list[Column]:
    cols: list[Column] = []
    size, _ = r.list_header()
    for i in range(size):
        name, ptype, rep, conv, nchildren = "", None, REQUIRED, None, 0
        for fid, ft in r.fields():
            if fid == 1:
                ptype = r.zigzag()
            elif fid == 3:
                rep = r.zigzag()
            elif fid == 4:
                name = r.binary().decode()
            elif fid == 5:
                nchildren = r.zigzag()
            elif fid == 6:
                conv = r.zigzag()
            else:
                r.skip(ft)
        if i == 0:
            if nchildren != size - 1:
                raise ParquetError("nested schemas not supported")
            continue                      # root element
        if ptype is None:
            raise ParquetError("nested schemas not supported")
        cols.append(Column(name, ptype, rep, conv))
    return cols


def _decode_chunk_meta(r: TReader) -> _ChunkMeta:
    m = _ChunkMeta()
    for fid, ft in r.fields():
        if fid == 3:                      # ColumnMetaData
            for cfid, cft in r.fields():
                if cfid == 1:
                    m.type = r.zigzag()
                elif cfid == 3:
                    n, _et = r.list_header()
                    m.path = [r.binary().decode() for _ in range(n)]
                elif cfid == 4:
                    m.codec = r.zigzag()
                elif cfid == 5:
                    m.num_values = r.zigzag()
                elif cfid == 7:
                    m.total_compressed_size = r.zigzag()
                elif cfid == 9:
                    m.data_page_offset = r.zigzag()
                elif cfid == 11:
                    m.dict_page_offset = r.zigzag()
                else:
                    r.skip(cft)
        else:
            r.skip(ft)
    return m


@dataclass
class _PageHeader:
    type: int = 0
    uncompressed_size: int = 0
    compressed_size: int = 0
    num_values: int = 0
    encoding: int = ENC_PLAIN


def _decode_page_header(r: TReader) -> _PageHeader:
    h = _PageHeader()
    for fid, ft in r.fields():
        if fid == 1:
            h.type = r.zigzag()
        elif fid == 2:
            h.uncompressed_size = r.zigzag()
        elif fid == 3:
            h.compressed_size = r.zigzag()
        elif fid in (5, 7):               # DataPageHeader/DictionaryPageHeader
            for pfid, pft in r.fields():
                if pfid == 1:
                    h.num_values = r.zigzag()
                elif pfid == 2:
                    h.encoding = r.zigzag()
                else:
                    r.skip(pft)
        else:
            r.skip(ft)
    return h


# ---------------------------------------------------------------------------
# value decoding
# ---------------------------------------------------------------------------

def _decompress(body: bytes, codec: int, want: int) -> bytes:
    if codec == CODEC_UNCOMPRESSED:
        return body
    if codec == CODEC_SNAPPY:
        return mtc.decompress_block(body)
    if codec == CODEC_GZIP:
        import gzip
        return gzip.decompress(body)
    raise ParquetError(f"unsupported codec {codec}")


# in-memory materialization cap: a 4-byte RLE run (or a forged
# num_rows) may legally DECLARE billions of values; materializing them
# from a small Select input is a decompression bomb, not a query
# (fuzz-tier finding).  64M values per chunk is far beyond any sane
# Select payload.
_MAX_VALUES = 1 << 26


def _read_rle_hybrid(buf: bytes, pos: int, end: int, bit_width: int,
                     count: int) -> list[int]:
    """RLE/bit-packed hybrid runs until `count` values are produced."""
    if count > _MAX_VALUES:
        raise ParquetError(f"value count {count} exceeds the in-memory "
                           f"reader limit")
    out: list[int] = []
    byte_width = (bit_width + 7) // 8
    while len(out) < count and pos < end:
        header = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        if header & 1:                     # bit-packed group
            groups = header >> 1
            nbits = groups * 8 * bit_width
            nbytes = (nbits + 7) // 8
            avail = max(0, min(nbytes, end - pos))
            bits = int.from_bytes(buf[pos:pos + avail], "little")
            pos += nbytes
            mask = (1 << bit_width) - 1
            # iterate only over bits the buffer actually holds: a
            # forged group count must not spin past the data
            have = (avail * 8) // bit_width if bit_width else 0
            for i in range(min(groups * 8, have)):
                if len(out) >= count:
                    break
                out.append((bits >> (i * bit_width)) & mask)
        else:                              # RLE run
            run = header >> 1
            v = int.from_bytes(buf[pos:pos + byte_width], "little") \
                if byte_width else 0
            pos += byte_width
            out.extend([v] * min(run, count - len(out)))
    if len(out) < count:
        raise ParquetError("truncated RLE/bit-packed run")
    return out


def _decode_plain(buf: bytes, ptype: int, count: int) -> list[Any]:
    vals: list[Any] = []
    pos = 0
    if ptype == INT32:
        return list(struct.unpack_from(f"<{count}i", buf, 0))
    if ptype == INT64:
        return list(struct.unpack_from(f"<{count}q", buf, 0))
    if ptype == DOUBLE:
        return list(struct.unpack_from(f"<{count}d", buf, 0))
    if ptype == FLOAT:
        return list(struct.unpack_from(f"<{count}f", buf, 0))
    if ptype == BOOLEAN:
        for i in range(count):
            vals.append(bool((buf[i // 8] >> (i % 8)) & 1))
        return vals
    if ptype == BYTE_ARRAY:
        for _ in range(count):
            n = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
            vals.append(bytes(buf[pos:pos + n]))
            pos += n
        return vals
    raise ParquetError(f"unsupported physical type {ptype}")


def _bit_width(maxval: int) -> int:
    return max(maxval.bit_length(), 0)


class ParquetReader:
    """Row-oriented reader over a flat parquet file held in memory."""

    def __init__(self, data: bytes):
        if len(data) < 12 or data[:4] != MAGIC or data[-4:] != MAGIC:
            raise ParquetError("not a parquet file (bad magic)")
        try:
            self._parse_footer(data)
        except ParquetError:
            raise
        except (struct.error, IndexError, UnicodeDecodeError,
                OverflowError, MemoryError, ValueError) as e:
            # truncated/corrupt metadata must surface as a parse error
            # (400), not an unhandled 500 — including non-UTF8 schema
            # names and absurd varint sizes (fuzz-tier findings)
            raise ParquetError(f"corrupt parquet metadata: {e}") from e

    def _parse_footer(self, data: bytes) -> None:
        footer_len = struct.unpack("<I", data[-8:-4])[0]
        meta = TReader(data[-8 - footer_len:-8])
        self.data = data
        self.columns: list[Column] = []
        self.num_rows = 0
        self._row_groups: list[tuple[int, list[_ChunkMeta]]] = []
        for fid, ft in meta.fields():
            if fid == 2:
                self.columns = _decode_schema(meta)
            elif fid == 3:
                self.num_rows = meta.zigzag()
            elif fid == 4:
                size, _ = meta.list_header()
                for _ in range(size):
                    rows, chunks = 0, []
                    for gfid, gft in meta.fields():
                        if gfid == 1:
                            n, _et = meta.list_header()
                            chunks = [_decode_chunk_meta(meta)
                                      for _ in range(n)]
                        elif gfid == 3:
                            rows = meta.zigzag()
                        else:
                            meta.skip(gft)
                    self._row_groups.append((rows, chunks))
            else:
                meta.skip(ft)
        self._by_name = {c.name: c for c in self.columns}

    # -- column chunk decode ------------------------------------------------

    def _read_chunk(self, meta: _ChunkMeta, col: Column,
                    rows: int) -> list[Any]:
        pos = meta.dict_page_offset \
            if meta.dict_page_offset is not None else meta.data_page_offset
        dictionary: Optional[list[Any]] = None
        values: list[Any] = []
        max_def = 1 if col.repetition == OPTIONAL else 0
        while len(values) < rows:
            r = TReader(self.data, pos)
            h = _decode_page_header(r)
            body = self.data[r.pos:r.pos + h.compressed_size]
            pos = r.pos + h.compressed_size
            body = _decompress(body, meta.codec, h.uncompressed_size)
            if h.type == PAGE_DICT:
                dictionary = _decode_plain(body, col.type, h.num_values)
                continue
            if h.type != PAGE_DATA:
                raise ParquetError(
                    f"unsupported page type {h.type} (need data page v1)")
            bpos = 0
            defs = None
            if max_def:
                dlen = struct.unpack_from("<I", body, 0)[0]
                defs = _read_rle_hybrid(body, 4, 4 + dlen,
                                        _bit_width(max_def), h.num_values)
                bpos = 4 + dlen
            present = h.num_values if defs is None \
                else sum(1 for d in defs if d == max_def)
            if h.encoding in (ENC_PLAIN_DICT, ENC_RLE_DICT):
                if dictionary is None:
                    raise ParquetError("dictionary page missing")
                bw = body[bpos]
                idx = _read_rle_hybrid(body, bpos + 1, len(body), bw,
                                       present)
                page_vals = [dictionary[i] for i in idx]
            elif h.encoding == ENC_PLAIN:
                page_vals = _decode_plain(body[bpos:], col.type, present)
            else:
                raise ParquetError(f"unsupported encoding {h.encoding}")
            if defs is None:
                values.extend(page_vals)
            else:
                it = iter(page_vals)
                values.extend(next(it) if d == max_def else None
                              for d in defs)
        return values[:rows]

    # -- row iteration ------------------------------------------------------

    def rows(self) -> Iterator[dict[str, Any]]:
        try:
            yield from self._rows_inner()
        except ParquetError:
            raise
        except (struct.error, IndexError, UnicodeDecodeError,
                OverflowError, MemoryError, ValueError) as e:
            raise ParquetError(f"corrupt parquet data: {e}") from e

    def _rows_inner(self) -> Iterator[dict[str, Any]]:
        for nrows, chunks in self._row_groups:
            table: dict[str, list[Any]] = {}
            for m in chunks:
                name = m.path[-1] if m.path else ""
                col = self._by_name.get(name)
                if col is None:
                    continue
                vals = self._read_chunk(m, col, nrows)
                if col.type == BYTE_ARRAY and col.converted == CT_UTF8:
                    vals = [v.decode("utf-8", "replace")
                            if isinstance(v, bytes) else v for v in vals]
                table[name] = vals
            for i in range(nrows):
                yield {name: table[name][i] for name in table}


# ---------------------------------------------------------------------------
# writer (tests + tooling): one row group, PLAIN, optional snappy
# ---------------------------------------------------------------------------

def _encode_plain(vals: list[Any], ptype: int) -> bytes:
    if ptype == INT32:
        return struct.pack(f"<{len(vals)}i", *vals)
    if ptype == INT64:
        return struct.pack(f"<{len(vals)}q", *vals)
    if ptype == DOUBLE:
        return struct.pack(f"<{len(vals)}d", *vals)
    if ptype == FLOAT:
        return struct.pack(f"<{len(vals)}f", *vals)
    if ptype == BOOLEAN:
        out = bytearray((len(vals) + 7) // 8)
        for i, v in enumerate(vals):
            if v:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)
    if ptype == BYTE_ARRAY:
        out = bytearray()
        for v in vals:
            b = v.encode() if isinstance(v, str) else bytes(v)
            out += struct.pack("<I", len(b)) + b
        return bytes(out)
    raise ParquetError(f"unsupported physical type {ptype}")


def _rle_bits(values: list[int], bit_width: int) -> bytes:
    """Encode as a single bit-packed group run (fine for test files)."""
    groups = (len(values) + 7) // 8
    out = bytearray()
    header = (groups << 1) | 1
    while True:
        if header < 0x80:
            out.append(header)
            break
        out.append((header & 0x7F) | 0x80)
        header >>= 7
    bits = 0
    for i, v in enumerate(values):
        bits |= (v & ((1 << bit_width) - 1)) << (i * bit_width)
    out += bits.to_bytes((groups * 8 * bit_width + 7) // 8, "little")
    return bytes(out)


def write_parquet(columns: list[Column], rows: list[dict[str, Any]],
                  codec: int = CODEC_UNCOMPRESSED) -> bytes:
    """Serialize rows into a single-row-group parquet file."""
    out = bytearray(MAGIC)
    chunk_metas: list[tuple[Column, int, int, int]] = []  # col, off, size, n
    for col in columns:
        vals = [r.get(col.name) for r in rows]
        max_def = 1 if col.repetition == OPTIONAL else 0
        body = bytearray()
        if max_def:
            defs = [0 if v is None else 1 for v in vals]
            enc = _rle_bits(defs, 1)
            body += struct.pack("<I", len(enc)) + enc
            present = [v for v in vals if v is not None]
        else:
            if any(v is None for v in vals):
                raise ParquetError(f"required column {col.name} has nulls")
            present = vals
        body += _encode_plain(present, col.type)
        raw = bytes(body)
        comp = mtc.compress_block(raw) if codec == CODEC_SNAPPY else raw
        # PageHeader
        w = TWriter()
        w.struct_begin()
        w.i32(1, PAGE_DATA)
        w.i32(2, len(raw))
        w.i32(3, len(comp))
        w.field(5, CT_STRUCT)              # DataPageHeader
        w.struct_begin()
        w.i32(1, len(vals))
        w.i32(2, ENC_PLAIN)
        w.i32(3, ENC_RLE)
        w.i32(4, ENC_RLE)
        w.struct_end()
        w.struct_end()
        off = len(out)
        out += w.out + comp
        chunk_metas.append((col, off, len(w.out) + len(comp), len(vals)))

    # FileMetaData footer
    w = TWriter()
    w.struct_begin()
    w.i32(1, 1)                            # version
    w.list_begin(2, CT_STRUCT, len(columns) + 1)
    w.struct_begin()                       # root schema element
    w.binary(4, b"schema")
    w.i32(5, len(columns))
    w.struct_end()
    for col in columns:
        w.struct_begin()
        w.i32(1, col.type)
        w.i32(3, col.repetition)
        w.binary(4, col.name.encode())
        if col.converted is not None:
            w.i32(6, col.converted)
        w.struct_end()
    w.i64(3, len(rows))                    # num_rows
    w.list_begin(4, CT_STRUCT, 1)          # row_groups
    w.struct_begin()
    w.list_begin(1, CT_STRUCT, len(chunk_metas))
    total = 0
    for col, off, size, n in chunk_metas:
        total += size
        w.struct_begin()                   # ColumnChunk
        w.i64(2, off)                      # file_offset
        w.field(3, CT_STRUCT)              # ColumnMetaData
        w.struct_begin()
        w.i32(1, col.type)
        w.list_begin(2, CT_I32, 1)
        w.zigzag(ENC_PLAIN)
        w.list_begin(3, CT_BINARY, 1)
        w.varint(len(col.name.encode()))
        w.out += col.name.encode()
        w.i32(4, codec)
        w.i64(5, n)
        w.i64(6, size)
        w.i64(7, size)
        w.i64(9, off)                      # data_page_offset
        w.struct_end()
        w.struct_end()
    w.i64(2, total)                        # total_byte_size
    w.i64(3, len(rows))                    # num_rows
    w.struct_end()
    w.struct_end()
    footer = bytes(w.out)
    out += footer
    out += struct.pack("<I", len(footer))
    out += MAGIC
    return bytes(out)


def parquet_records(data: bytes) -> Iterator[dict[str, Any]]:
    """Record stream for the select engine (records.py reader shape).
    The footer parses eagerly so a bad file fails before iteration."""
    return ParquetReader(data).rows()
